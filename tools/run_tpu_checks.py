#!/usr/bin/env python
"""One-shot TPU validation + benchmark suite (run when the chip is up).

Produces ``tpu_checks_report.json`` covering the TPU-dependent backlog:

1. **bench**: the headline ResNet-50 batch-32 number (bench.py child) plus
   batch-128/256 variants with MFU — the batch-scaling view of the MFU
   ceiling.
2. **pallas_rnn**: fused LSTM/GRU kernels on real Mosaic — correctness vs
   the lax.scan reference and fwd timing, deciding USE_PALLAS_RNN.
3. **flash_attention**: block-size sweep for head_dim 64 and 128
   (fwd and fwd+bwd) vs XLA attention.
4. **consistency**: the registry-wide op sweep's forward SPECS replayed on
   TPU vs CPU with fp32/bf16 tolerance tiers — the reference's
   test_operator_gpu.py check_consistency trick (test_utils.py:1207).

Relay-safe: probes the backend in a bounded subprocess first (bench.py's
probe); exits with a parseable "tpu_unavailable" report if wedged.

Run:  python tools/run_tpu_checks.py [--skip consistency ...]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, ROOT)

REPORT = os.path.join(ROOT, "tpu_checks_report.json")


def _flush(report, path=REPORT):
    """Persist partial results — the relay can wedge mid-run and a
    killed process must not lose the variants already measured."""
    with open(path, "w") as f:
        json.dump(report, f, indent=2)


def check_roofline(report):
    """Raw achievable ceilings through this relay: bf16 matmul TFLOP/s,
    HBM read+write bandwidth, and per-dispatch latency. Separates
    'environment is throttled' from 'the model code is slow' when reading
    the bench MFU numbers."""
    import jax
    import jax.numpy as jnp
    from mxtpu.benchmarking import timed_loop, hostsync
    # resume-friendly: a timeout-killed attempt keeps its finished keys
    # (merged into the report by the parent), so retries skip them
    res = report.get("roofline") or {}
    report["roofline"] = res
    for n in (4096, 8192):
        if "matmul_bf16_%d_tflops" % n in res:
            continue
        # chained (x @ b) * 1/sqrt(n): every iteration's input depends on
        # the previous output, so no dispatch can be elided or memoized;
        # the rescale keeps the chain numerically bounded
        b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)
        x0 = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
        f = jax.jit(lambda x: (x @ b) * (1.0 / np.sqrt(n)))
        sec, _ = timed_loop(lambda s, x0=x0, f=f:
                            f(x0 if s is None else s))
        res["matmul_bf16_%d_tflops" % n] = round(2 * n ** 3 / sec / 1e12, 2)
        _flush(report)
    # HBM stream: big fp32 elementwise, chained through y (reads 2 buffers
    # + writes 1 per iteration)
    if "hbm_stream_gbs" not in res:
        n = 16 * 1024 * 1024
        x = jnp.ones((n,), jnp.float32)
        y0 = jnp.zeros((n,), jnp.float32)
        g = jax.jit(lambda y: x + y * 1e-9)
        sec, _ = timed_loop(lambda s: g(y0 if s is None else s),
                            lo_iters=8, max_iters=2048)
        res["hbm_stream_gbs"] = round(3 * 4 * n / sec / 1e9, 1)
        _flush(report)
    # dispatch-enqueue latency: issue many tiny chained ops, no sync in
    # the loop; the final hostsync is amortized over the count
    if "dispatch_us" not in res:
        t0h = jnp.ones((8,), jnp.float32)
        h = jax.jit(lambda t: t + 1)
        t = h(t0h)
        hostsync(t)
        k = 500
        t1 = time.perf_counter()
        for _ in range(k):
            t = h(t)
        enq = (time.perf_counter() - t1) / k     # pure enqueue rate
        hostsync(t)
        res["dispatch_enqueue_us"] = round(enq * 1e6, 1)
        # executed round-trip rate of the same chain, overhead-cancelled
        sec, _ = timed_loop(lambda s: h(t0h if s is None else s),
                            lo_iters=64, min_work_s=0.05, max_iters=2048)
        res["dispatch_us"] = round(sec * 1e6, 1)
        _flush(report)


def _bench_variants(report, combos):
    """ResNet-50 fused-step throughput at (batch, nhwc, remat) combos —
    layout is the MFU lever, batch scaling shows the ceiling, remat shows
    the HBM headroom lever."""
    import jax
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import MeshContext, ShardedTrainer
    from bench import (BASELINE_IMG_S, RESNET50_TRAIN_FLOPS_PER_IMG,
                       peak_tflops)
    kind = getattr(jax.devices()[0], "device_kind", "")
    peak = peak_tflops(kind) or 0.0
    for combo in combos:
        batch, nhwc, remat = combo[:3]
        auto = combo[3] if len(combo) > 3 else False
        key = "bench_batch%d%s%s%s" % (batch, "_nhwc" if nhwc else "",
                                       "_remat" if remat else "",
                                       "_auto" if auto else "")
        if isinstance(report.get(key), dict) and \
                "img_per_sec" in report[key]:
            continue  # measured in an earlier window
        try:
            if nhwc:
                os.environ["MXTPU_CONV_LAYOUT"] = "NHWC"
            else:
                os.environ.pop("MXTPU_CONV_LAYOUT", None)
            mx.random.seed(0)
            net = vision.get_resnet(1, 50)
            net.initialize(mx.init.Xavier(), force_reinit=True)
            x = np.random.uniform(0, 1, (batch, 3, 224, 224)).astype("f")
            y = np.random.randint(0, 1000, (batch,)).astype("f")
            net(mx.nd.array(x[:1]))
            st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                "sgd", {"learning_rate": 0.05,
                                        "momentum": 0.9, "wd": 1e-4},
                                mesh=MeshContext(jax.devices()[:1], data=1),
                                dtype="bfloat16", remat=remat,
                                auto_layout=auto)
            for _ in range(3):
                st.step(x, y)
            xd = st._shard_batch([x])[0]
            yd = st._shard_batch([y])[0]
            # steps chain naturally through the optimizer state, so the
            # difference-timed loop (honest host-fetch sync; see
            # mxtpu/benchmarking.py) needs no input rewiring
            from mxtpu.benchmarking import timed_loop
            sec, _ = timed_loop(lambda _s: st.step_async(xd, yd),
                                lo_iters=4, min_work_s=1.0, max_iters=256)
            img_s = batch / sec
            entry = {"img_per_sec": round(img_s, 1),
                     "vs_baseline": round(img_s / BASELINE_IMG_S, 2)}
            if peak:
                entry["mfu"] = round(
                    img_s * RESNET50_TRAIN_FLOPS_PER_IMG / (peak * 1e12), 4)
            report[key] = entry
        except Exception as e:
            report[key] = {"error": repr(e)}
        finally:
            os.environ.pop("MXTPU_CONV_LAYOUT", None)
            _flush(report)


def check_bench(report):
    """The like-for-like headline first: ResNet-50 train batch 32, the
    exact configuration of the reference's P100 181.53 img/s row
    (perf.md:185) — one number that settles vs_baseline even if the
    relay window closes right after."""
    b32 = report.get("bench_batch32")
    b32_good = (isinstance(b32, dict) and b32.get("value", 0) > 0
                and not b32.get("error")
                and not b32.get("tpu_unavailable"))
    if not b32_good:
        try:
            out = subprocess.run(
                [sys.executable, os.path.join(ROOT, "bench.py")],
                capture_output=True, text=True, timeout=1500)
            line = (out.stdout.strip().splitlines()[-1]
                    if out.stdout.strip() else "{}")
            report["bench_batch32"] = json.loads(line)
        except Exception as e:
            report["bench_batch32"] = {"error": repr(e)}
        _flush(report)


def check_bench_nhwc(report):
    # the layout lever next: NHWC vs the b32/b128 NCHW anchors is the
    # main single-chip MFU decision
    _bench_variants(report, ((128, True, False), (256, True, False)))


def check_bench_scale(report):
    # batch scaling + remat headroom, valuable but after the headline
    # and the layout decision
    _bench_variants(report, ((128, False, False), (256, False, False),
                             (512, False, False), (512, False, True)))


def check_bench_smallbatch(report):
    """The rest of the reference's P100 training table (perf.md:176-185
    publishes batch 1-32): small batches are dispatch/latency-bound on
    any accelerator, so this is the honest worst-case end of the curve.
    b64 fills the 32-128 gap; a prior-window b128 outlier (808 img/s vs
    the 2.0-2.3k plateau, relay hiccup) is moved aside and re-measured."""
    outlier = report.get("bench_batch128")
    if isinstance(outlier, dict) and \
            outlier.get("img_per_sec", 0) < 1500 and \
            "bench_batch128_outlier" not in report:
        report["bench_batch128_outlier"] = outlier
        # overwrite rather than delete: the parent merges child output
        # with dict.update(), which cannot propagate a deletion — an
        # img_per_sec-free placeholder makes _bench_variants re-measure
        # and survives a timeout-kill between here and the re-measure
        report["bench_batch128"] = {"remeasuring": True}
        _flush(report)
    _bench_variants(report, ((1, False, False), (2, False, False),
                             (4, False, False), (8, False, False),
                             (16, False, False), (64, False, False),
                             (128, False, False)))


def check_profile(report):
    """Trace real training steps on TPU: jax.profiler XPlane dump plus the
    perfetto/chrome trace it contains, committed under docs/traces/ so
    fusion boundaries (e.g. around BatchNorm) can be inspected offline."""
    import glob
    import shutil
    import jax
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import MeshContext, ShardedTrainer
    from bench import best_measured_config

    res = {}
    report["profile"] = res
    tuned = best_measured_config() or (32, False)
    batch, nhwc = tuned
    trace_root = os.path.join(ROOT, "docs", "traces")
    final_xp_dir = os.path.join(trace_root, "xplane")
    # trace into a scratch dir and swap in only on success — a failed
    # retry must not destroy previously committed trace evidence
    xp_dir = os.path.join(trace_root, ".xplane_tmp")
    shutil.rmtree(xp_dir, ignore_errors=True)
    os.makedirs(xp_dir, exist_ok=True)
    try:
        if nhwc:
            os.environ["MXTPU_CONV_LAYOUT"] = "NHWC"
        mx.random.seed(0)
        net = vision.get_resnet(1, 50)
        net.initialize(mx.init.Xavier(), force_reinit=True)
        x = np.random.uniform(0, 1, (batch, 3, 224, 224)).astype("f")
        y = np.random.randint(0, 1000, (batch,)).astype("f")
        net(mx.nd.array(x[:1]))
        st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "sgd", {"learning_rate": 0.05,
                                    "momentum": 0.9, "wd": 1e-4},
                            mesh=MeshContext(jax.devices()[:1], data=1),
                            dtype="bfloat16")
        for _ in range(3):
            st.step(x, y)
        xd = st._shard_batch([x])[0]
        yd = st._shard_batch([y])[0]
        from mxtpu.benchmarking import hostsync
        t0 = time.perf_counter()
        with jax.profiler.trace(xp_dir):
            last = None
            for _ in range(5):
                last = st.step_async(xd, yd)
            hostsync(last)   # wait_to_read can lie through the relay
        res["traced_steps"] = 5
        res["batch"] = batch
        res["layout"] = "NHWC" if nhwc else "NCHW"
        # includes one ~50-90 ms relay sync: a floor, not the headline
        res["img_per_sec_traced"] = round(
            5 * batch / (time.perf_counter() - t0), 1)
        found = sorted(glob.glob(os.path.join(
            xp_dir, "**", "*trace.json.gz"), recursive=True))
        if found:
            dst = os.path.join(trace_root, "resnet50_step_trace.json.gz")
            shutil.copy(found[0], dst)
            res["chrome_trace"] = os.path.relpath(dst, ROOT)
        if glob.glob(os.path.join(xp_dir, "**", "*.xplane.pb"),
                     recursive=True):
            shutil.rmtree(final_xp_dir, ignore_errors=True)
            os.rename(xp_dir, final_xp_dir)
            xplanes = sorted(glob.glob(os.path.join(
                final_xp_dir, "**", "*.xplane.pb"), recursive=True))
            res["xplane"] = os.path.relpath(xplanes[0], ROOT)
    except Exception as e:
        res["error"] = repr(e)[:300]
    finally:
        os.environ.pop("MXTPU_CONV_LAYOUT", None)
        shutil.rmtree(xp_dir, ignore_errors=True)
    _flush(report)


def check_io_pipeline(report):
    """The real-data path: synthetic-ImageNet RecordIO shards (im2rec)
    feeding the TPU training step through the native ImageRecordIter —
    decode rate vs device rate decides 'IO is provably not the
    bottleneck' (reference methodology: train_imagenet.py over
    iter_image_recordio_2.cc)."""
    import tempfile

    sys.path.insert(0, os.path.join(ROOT, "tools"))

    res = {}
    report["io_pipeline"] = res
    tiny = os.environ.get("MXTPU_IO_STAGE_TINY") == "1"  # CPU dry-run
    batch, n_images = (8, 64) if tiny else (128, 640)
    root = tempfile.mkdtemp(prefix="mxtpu_io_tpu_")
    try:
        _check_io_pipeline_body(report, res, root, batch, n_images)
    finally:
        import shutil
        shutil.rmtree(root, ignore_errors=True)


def _check_io_pipeline_body(report, res, root, batch, n_images):
    import jax
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import MeshContext, ShardedTrainer
    from bench_io import gen_dataset, measure_iter
    t0 = time.perf_counter()
    shards = gen_dataset(root, n_images, size=360, n_shards=2)
    res["dataset_gen_s"] = round(time.perf_counter() - t0, 1)
    _flush(report)
    common = dict(data_shape=(3, 224, 224), batch_size=batch,
                  shuffle=True, rand_crop=True, rand_mirror=True,
                  mean_r=123.68, mean_g=116.78, mean_b=103.94,
                  std_r=58.4, std_g=57.1, std_b=57.4, resize=256)

    # standalone decode rate through the public iterator (host-side)
    try:
        res["decode_img_s"] = round(measure_iter(
            lambda: mx.io.ImageRecordIter(path_imgrec=shards[0], **common),
            n_batches=5, batch_size=batch), 1)
    except Exception as e:
        res["decode_error"] = repr(e)[:300]
    _flush(report)

    # end-to-end: iterator batches -> host->device transfer -> train step
    try:
        mx.random.seed(0)
        net = vision.get_resnet(1, 50)
        net.initialize(mx.init.Xavier(), force_reinit=True)
        net(mx.nd.array(np.zeros((1, 3, 224, 224), "f")))
        st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "sgd", {"learning_rate": 0.05,
                                    "momentum": 0.9, "wd": 1e-4},
                            mesh=MeshContext(jax.devices()[:1], data=1),
                            dtype="bfloat16")
        it = mx.io.ImageRecordIter(path_imgrec=shards[0], **common)
        first = next(iter(it))
        st.step(first.data[0].asnumpy(), first.label[0].asnumpy())  # compile
        it.reset()
        from mxtpu.benchmarking import hostsync
        n_img = 0
        t0 = time.perf_counter()
        last = None
        for b in it:
            last = st.step_async(*st._shard_batch(
                [b.data[0].asnumpy(), b.label[0].asnumpy()]))
            n_img += batch - (b.pad or 0)
        if last is not None:
            hostsync(last)   # wait_to_read can lie through the relay
        res["train_e2e_img_s"] = round(n_img / (time.perf_counter() - t0), 1)
        if hasattr(it, "close"):
            it.close()
    except Exception as e:
        res["train_error"] = repr(e)[:300]

    # verdict: decode keeps up with the fastest measured device rate
    best_dev = 0.0
    for key, entry in report.items():
        if key.startswith("bench_batch") and isinstance(entry, dict):
            best_dev = max(best_dev, entry.get("img_per_sec")
                           or entry.get("value") or 0)
    res["best_device_img_s"] = best_dev
    if "decode_img_s" in res and best_dev:
        res["io_not_bottleneck"] = bool(res["decode_img_s"] >= best_dev)
    _flush(report)


def check_inference(report):
    """benchmark_score tier (reference docs/faq/perf.md:107-144 P100
    inference tables: ResNet-50 713.17, VGG 854.4, Inc-v3 493.72 img/s
    at batch 32): forward-only throughput through the hybridized zoo
    nets, fp32 (the reference's methodology) and bf16 (the TPU-native
    serving dtype), NCHW and NHWC."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "benchmark_score", os.path.join(
            ROOT, "example", "image-classification",
            "benchmark_score.py"))
    bs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bs)
    import mxtpu as mx

    res = report.setdefault("inference", {})
    baselines = {"resnet-50": 713.17, "vgg16": 854.4,
                 "inception-v3": 493.72}    # perf.md:144, P100 batch 32
    for name, baseline in baselines.items():
        hw = 299 if name == "inception-v3" else 224
        for dtype in ("float32", "bfloat16"):
            for nhwc in (False, True):
                key = "%s_b32_%s%s" % (name, dtype,
                                       "_nhwc" if nhwc else "")
                if "img_per_sec" in res.get(key, {}):
                    continue   # real number from an earlier window
                try:
                    if nhwc:
                        os.environ["MXTPU_CONV_LAYOUT"] = "NHWC"
                    else:
                        os.environ.pop("MXTPU_CONV_LAYOUT", None)
                    # the ONE timing methodology (perf.md's) lives in
                    # benchmark_score.score; vs_baseline stays honest
                    img_s = bs.score(name, 32, hw, n_iter=20,
                                     dtype=dtype)
                    res[key] = {"img_per_sec": round(img_s, 1),
                                "vs_baseline": round(img_s / baseline,
                                                     2)}
                except Exception as e:
                    res[key] = {"error": repr(e)[:200]}
                finally:
                    os.environ.pop("MXTPU_CONV_LAYOUT", None)
                _flush(report)


def check_bench_autolayout(report):
    """AUTO persistent-state layouts (ShardedTrainer(auto_layout=True)):
    the round-5 trace attributes ~22% of step time to layout copies, a
    chunk of which are conv-weight relayouts between the optimizer's
    default layout and the convolution's preferred one. AUTO lets XLA
    keep the state in the preferred layout across steps. Measured at the
    headline batch and the large-batch anchor."""
    _bench_variants(report, ((32, False, False, True),
                             (256, False, False, True)))


def _transformer_train_step(layers, d_model, heads, seq, vocab, attn):
    """(init_fn, step_fn, flops_per_step) for a causal pre-LN
    transformer LM train step — the long-context training workload the
    reference has no counterpart for (its sequence tooling is bucketed
    RNNs, SURVEY §5.7). attn='flash' routes through the Pallas kernels
    (mxtpu/ops/pallas_attention.py), attn='xla' through the naive
    einsum+softmax path; both bf16 compute, fp32 master weights + SGD."""
    import jax
    import jax.numpy as jnp
    from mxtpu.ops.pallas_attention import flash_attention
    d_head = d_model // heads

    def init(key):
        ks = jax.random.split(key, 2 + 7 * layers)
        s = 0.02
        p = {"emb": jax.random.normal(ks[0], (vocab, d_model)) * s,
             "head": jax.random.normal(ks[1], (d_model, vocab)) * s}
        for i in range(layers):
            k7 = ks[2 + 7 * i: 9 + 7 * i]
            p["b%d" % i] = {
                "wq": jax.random.normal(k7[0], (d_model, d_model)) * s,
                "wk": jax.random.normal(k7[1], (d_model, d_model)) * s,
                "wv": jax.random.normal(k7[2], (d_model, d_model)) * s,
                "wo": jax.random.normal(k7[3], (d_model, d_model)) * s,
                "w1": jax.random.normal(k7[4], (d_model, 4 * d_model)) * s,
                "w2": jax.random.normal(k7[5], (4 * d_model, d_model)) * s,
                "ln": jnp.ones((2, d_model))}
        return p

    def _ln(x, g):
        m = x.mean(-1, keepdims=True)
        v = ((x - m) ** 2).mean(-1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + 1e-5) * g

    def _attend(q, k, v):
        if attn == "flash":
            return flash_attention(q, k, v, causal=True,
                                   block_q=1024, block_k=1024)
        T = q.shape[2]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d_head)
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -1e9)
        w = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", w, v)

    def fwd(params, tokens):
        B, T = tokens.shape
        h = params["emb"].astype(jnp.bfloat16)[tokens]
        for i in range(layers):
            b = {k: v.astype(jnp.bfloat16) for k, v in
                 params["b%d" % i].items()}
            x = _ln(h, b["ln"][0])
            qkv = [(x @ b[w]).reshape(B, T, heads, d_head)
                   .transpose(0, 2, 1, 3) for w in ("wq", "wk", "wv")]
            a = _attend(*qkv).transpose(0, 2, 1, 3).reshape(B, T, d_model)
            h = h + a @ b["wo"]
            x = _ln(h, b["ln"][1])
            h = h + jax.nn.gelu(x @ b["w1"]) @ b["w2"]
        return h @ params["head"].astype(jnp.bfloat16)

    def loss_fn(params, tokens):
        logits = fwd(params, tokens[:, :-1]).astype(jnp.float32)
        tgt = tokens[:, 1:]
        lse = jax.nn.logsumexp(logits, -1)
        picked = jnp.take_along_axis(logits, tgt[..., None], -1)[..., 0]
        return (lse - picked).mean()

    def step(params, tokens, lr):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        new = jax.tree_util.tree_map(lambda w, g: w - lr * g,
                                     params, grads)
        return new, loss

    # matmul weight-element count: head projection + per-layer
    # qkv/o (4d^2) and MLP (8d^2); the embedding lookup is a gather,
    # not FLOPs
    n_mm = vocab * d_model + layers * 12 * d_model * d_model
    return init, step, n_mm


def check_transformer_train(report):
    """Long-context transformer LM training on one chip: 8k causal
    sequence, bf16, flash (Pallas) vs naive XLA attention inside the
    SAME full train step — tokens/sec and MFU. The modern counterpart
    of the CNN headline; no reference baseline exists (MXNet 1.1
    predates transformers), so the comparison is flash-vs-xla and
    absolute MFU."""
    import jax
    import jax.numpy as jnp
    from mxtpu.benchmarking import timed_loop
    from bench import peak_tflops
    layers, d_model, heads, seq, vocab, batch = 4, 512, 8, 8192, 32000, 1
    kind = getattr(jax.devices()[0], "device_kind", "")
    peak = peak_tflops(kind) or 0.0
    res = report.setdefault("transformer_train", {})
    res["config"] = {"layers": layers, "d_model": d_model, "heads": heads,
                     "seq": seq, "vocab": vocab, "batch": batch,
                     "dtype": "bfloat16"}
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, (batch, seq + 1)))
    for attn in ("flash", "xla"):
        if "tokens_per_sec" in res.get(attn, {}):
            continue
        try:
            init, step, n_mm = _transformer_train_step(
                layers, d_model, heads, seq, vocab, attn)
            params = init(jax.random.PRNGKey(0))
            jstep = jax.jit(step, donate_argnums=(0,))
            params, _ = jstep(params, tokens, 1e-3)  # compile + settle

            carry = {"p": params}

            def one(_s):
                carry["p"], loss = jstep(carry["p"], tokens, 1e-3)
                return loss
            sec, _ = timed_loop(one, lo_iters=2, min_work_s=1.0,
                                max_iters=64)
            toks = batch * seq / sec
            # fwd matmul FLOPs: 2*T*n_mm_params; attention:
            # 2 * 2*B*H*T^2*d_head, halved for causal; train = 3x fwd
            attn_fl = 2 * 2 * batch * heads * seq ** 2 * (
                d_model // heads) * 0.5
            fl_step = 3 * (2 * batch * seq * n_mm + attn_fl)
            entry = {"tokens_per_sec": round(toks, 1),
                     "step_ms": round(sec * 1e3, 2)}
            if peak:
                entry["mfu"] = round(fl_step / sec / (peak * 1e12), 4)
            res[attn] = entry
        except Exception as e:
            res[attn] = {"error": repr(e)[:200]}
        _flush(report)


def check_inference_smallbatch(report):
    """The latency-bound rows of the reference's P100 inference tables
    (perf.md:107-144 publishes batch 1-32): batch 1 and 8, fp32 NCHW —
    the reference's own methodology — plus the relay's ~2.4 ms dispatch
    floor working against us, which makes these the honest worst case."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "benchmark_score", os.path.join(
            ROOT, "example", "image-classification",
            "benchmark_score.py"))
    bs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bs)

    res = report.setdefault("inference", {})
    baselines = {  # perf.md P100 table rows, images/sec
        ("resnet-50", 1): 162.27, ("resnet-50", 8): 529.34,
        ("vgg16", 1): 294.6, ("vgg16", 8): 522.9,
        ("inception-v3", 1): 80.17, ("inception-v3", 8): 319.52,
    }
    for (name, batch), baseline in baselines.items():
        hw = 299 if name == "inception-v3" else 224
        key = "%s_b%d_float32" % (name, batch)
        if "img_per_sec" in res.get(key, {}):
            continue   # real number from an earlier window
        try:
            img_s = bs.score(name, batch, hw, n_iter=20, dtype="float32")
            res[key] = {"img_per_sec": round(img_s, 1),
                        "vs_baseline": round(img_s / baseline, 2)}
        except Exception as e:
            res[key] = {"error": repr(e)[:200]}
        _flush(report)


def check_pallas_rnn(report):
    import jax
    import jax.numpy as jnp
    from mxtpu.ops import pallas_rnn

    rng = np.random.RandomState(0)
    T, B, H = 128, 32, 256
    res = {}
    report["pallas_rnn"] = res  # mutated in place; flushed per cell type
    # LSTM: pallas fused vs scan reference
    x_proj = jnp.asarray(rng.randn(T, B, 4 * H).astype("f"))
    h0 = jnp.asarray(rng.randn(B, H).astype("f"))
    c0 = jnp.asarray(rng.randn(B, H).astype("f"))
    wh_t = jnp.asarray((rng.randn(H, 4 * H) / np.sqrt(H)).astype("f"))
    fused = jax.jit(pallas_rnn.lstm_scan)
    ref = jax.jit(pallas_rnn._scan_reference)
    out_f = jax.block_until_ready(fused(x_proj, h0, c0, wh_t))
    out_r = jax.block_until_ready(ref(x_proj, h0, c0, wh_t))
    err = max(float(jnp.max(jnp.abs(a - b)))
              for a, b in zip(jax.tree.leaves(out_f), jax.tree.leaves(out_r)))
    res["lstm_max_abs_err"] = err
    # chain the recurrent state between iterations: honest through the
    # relay AND immune to repeated-dispatch memoization
    from mxtpu.benchmarking import timed_loop

    def _lstm_step(fn):
        def step(s):
            h, c = (h0, c0) if s is None else s
            _ys, hT, cT = fn(x_proj, h, c, wh_t)
            return hT, cT
        return step
    sec, _ = timed_loop(_lstm_step(fused), min_work_s=0.3)
    res["lstm_pallas_ms"] = round(sec * 1e3, 3)
    sec, _ = timed_loop(_lstm_step(ref), min_work_s=0.3)
    res["lstm_scan_ms"] = round(sec * 1e3, 3)
    _flush(report)

    # GRU
    x3 = jnp.asarray(rng.randn(T, B, 3 * H).astype("f"))
    whrz = jnp.asarray((rng.randn(H, 2 * H) / np.sqrt(H)).astype("f"))
    whn = jnp.asarray((rng.randn(H, H) / np.sqrt(H)).astype("f"))
    bhn = jnp.asarray(rng.randn(H).astype("f") * 0.1)
    gfused = jax.jit(pallas_rnn.gru_scan)
    gref = jax.jit(pallas_rnn._gru_scan_reference)
    out_f = jax.block_until_ready(gfused(x3, h0, whrz, whn, bhn))
    out_r = jax.block_until_ready(gref(x3, h0, whrz, whn, bhn))
    err = max(float(jnp.max(jnp.abs(a - b)))
              for a, b in zip(jax.tree.leaves(out_f), jax.tree.leaves(out_r)))
    res["gru_max_abs_err"] = err

    def _gru_step(fn):
        def step(h):
            _ys, hT = fn(x3, h0 if h is None else h, whrz, whn, bhn)
            return hT
        return step
    sec, _ = timed_loop(_gru_step(gfused), min_work_s=0.3)
    res["gru_pallas_ms"] = round(sec * 1e3, 3)
    sec, _ = timed_loop(_gru_step(gref), min_work_s=0.3)
    res["gru_scan_ms"] = round(sec * 1e3, 3)
    # USE_PALLAS_RNN gates BOTH cell types (ops/rnn.py), so both must be
    # correct and the fused kernels must win before recommending it
    res["recommend_use_pallas_rnn"] = bool(
        res["lstm_max_abs_err"] < 1e-3 and
        res["gru_max_abs_err"] < 1e-3 and
        res["lstm_pallas_ms"] < res["lstm_scan_ms"] and
        res["gru_pallas_ms"] < res["gru_scan_ms"])
    _flush(report)


def check_flash_attention(report):
    import jax
    import jax.numpy as jnp
    from mxtpu.ops.pallas_attention import flash_attention

    rng = np.random.RandomState(0)
    res = {}
    report["flash_attention"] = res  # mutated in place; flushed per d
    for d in (64, 128):
        B, Hh, T = 1, 8, 8192
        q = jnp.asarray(rng.randn(B, Hh, T, d).astype(np.float32)
                        ).astype(jnp.bfloat16)
        k = jnp.asarray(rng.randn(B, Hh, T, d).astype(np.float32)
                        ).astype(jnp.bfloat16)
        v = jnp.asarray(rng.randn(B, Hh, T, d).astype(np.float32)
                        ).astype(jnp.bfloat16)

        def xla_attn(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask, s, -1e9)
            return jnp.einsum("bhqk,bhkd->bhqd",
                              jax.nn.softmax(s.astype(jnp.float32), -1
                                             ).astype(q.dtype), v)

        from mxtpu.benchmarking import timed_loop

        def _attn_timer(fn):
            # the output has q's shape: chain it in as the next query so
            # each dispatch differs (attention of attention is still a
            # bounded weighted average of v)
            def step(s):
                return fn(q if s is None else s, k, v)
            sec, _ = timed_loop(step, lo_iters=2, min_work_s=0.3,
                                max_iters=64)
            return sec

        xla_j = jax.jit(xla_attn)
        try:
            res["xla_fwd_ms_d%d" % d] = round(_attn_timer(xla_j) * 1e3, 2)
        except Exception as e:
            res["xla_fwd_ms_d%d" % d] = repr(e)

        best = None
        for bq in (256, 512, 1024):
            for bk in (512, 1024, 2048):
                try:
                    f = jax.jit(lambda q, k, v, bq=bq, bk=bk:
                                flash_attention(q, k, v, causal=True,
                                                block_q=bq, block_k=bk))
                    ms = _attn_timer(f) * 1e3
                    res["flash_fwd_ms_d%d_q%d_k%d" % (d, bq, bk)] = \
                        round(ms, 2)
                    if best is None or ms < best[0]:
                        best = (ms, bq, bk)
                except Exception as e:
                    res["flash_fwd_ms_d%d_q%d_k%d" % (d, bq, bk)] = \
                        repr(e)[:120]
        if best:
            res["best_d%d" % d] = {"ms": round(best[0], 2),
                                   "block_q": best[1], "block_k": best[2]}

        # fwd+bwd at the best block size
        if best:
            _, bq, bk = best

            def loss(q, k, v):
                return flash_attention(q, k, v, causal=True, block_q=bq,
                                       block_k=bk).astype(jnp.float32).sum()
            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            try:
                from mxtpu.benchmarking import chain_input

                def gstep(s):
                    dq, _dk, _dv = g(q if s is None else s, k, v)
                    # next query = original q with a zero-valued
                    # dependency on this iteration's gradient
                    return chain_input(q, dq)
                sec, _ = timed_loop(gstep, lo_iters=2, min_work_s=0.3,
                                    max_iters=64)
                res["flash_fwdbwd_ms_d%d" % d] = round(sec * 1e3, 2)
            except Exception as e:
                res["flash_fwdbwd_ms_d%d" % d] = repr(e)[:120]
        _flush(report)


_CONSISTENCY_META = ("__complete__", "__spec_hash__")


def _sweep_spec_hash():
    """Identity of the sweep SPECS a cached leg pickle was computed
    against — a cached CPU reference must be invalidated when
    test_op_sweep.py changes, or fresh TPU outputs get compared to
    stale reference outputs and report false mismatches."""
    import hashlib
    with open(os.path.join(ROOT, "tests", "test_op_sweep.py"), "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def _load_leg_pickle(path, spec_hash):
    """A leg pickle, or None if absent/unreadable/stale."""
    import pickle
    try:
        with open(path, "rb") as f:
            d = pickle.load(f)
    except Exception:
        return None
    if not isinstance(d, dict) or d.get("__spec_hash__") != spec_hash:
        return None
    return d


def _consistency_leg(out_path):
    """Compute forward outputs for every non-stateful sweep SPEC on the
    CURRENT process's default JAX backend and pickle {op: [arrays]}.
    Run once under JAX_PLATFORMS=cpu (reference leg) and once in the
    axon/TPU process — the axon relay registers only its own backend,
    so the two legs cannot share an interpreter.

    Resumable: results flush periodically, and a tiny sentinel file
    records the op in flight so a timeout-killed attempt continues where
    it stopped. An op left in flight by TWO consecutive kills is recorded
    as an error and skipped — one kill is as likely the stage's
    cumulative timeout expiring on a healthy (slow) op as a relay wedge,
    so a single strike must not blacklist it."""
    import importlib.util
    import pickle
    spec_mod = importlib.util.spec_from_file_location(
        "op_sweep_specs", os.path.join(ROOT, "tests", "test_op_sweep.py"))
    sweep = importlib.util.module_from_spec(spec_mod)
    spec_mod.loader.exec_module(sweep)
    import mxtpu as mx
    import mxtpu.ndarray as nd

    spec_hash = _sweep_spec_hash()
    outs = _load_leg_pickle(out_path, spec_hash) or {}
    outs["__spec_hash__"] = spec_hash
    outs.pop("__complete__", None)

    sent_path = out_path + ".inflight"
    wedged_prior = {}
    if os.path.exists(sent_path):
        try:
            with open(sent_path) as f:
                nm, _, cnt = f.read().strip().partition(":")
            if nm:
                wedged_prior[nm] = int(cnt or 1)
        except Exception:
            pass

    def flush():
        with open(out_path, "wb") as f:
            pickle.dump(outs, f)

    canonical = sweep._canonical_ops()
    unflushed = 0
    for name in sorted(sweep.SPECS):
        spec = sweep.SPECS[name]
        if canonical[name].stateful:
            continue  # different backends draw identical keys, but skip
        if name in outs:
            continue
        if wedged_prior.get(name, 0) >= 2:
            outs[name] = ("error: unfinished in 2 prior attempts "
                          "(relay wedge or stage timeout)")
            flush()
            unflushed = 0
            os.unlink(sent_path)
            continue
        r = np.random.RandomState(sweep._seed(name))
        try:
            args = spec.args(r)
        except Exception:
            continue
        with open(sent_path, "w") as f:
            f.write("%s:%d" % (name, wedged_prior.get(name, 0) + 1))
        try:
            mx.random.seed(0)
            o = getattr(nd, name)(
                *[nd.array(a) if isinstance(a, np.ndarray) else a
                  for a in args], **spec.params)
            o = o if isinstance(o, (list, tuple)) else [o]
            outs[name] = [np.asarray(x.asnumpy()) for x in o]
        except Exception as e:
            outs[name] = "error: " + repr(e)[:200]
        unflushed += 1
        if unflushed >= 10:
            # batch the full-pickle rewrites (O(n^2) bytes if per-op);
            # a kill loses at most the last <10 results, which the next
            # attempt recomputes — only the sentinel needs per-op writes
            flush()
            unflushed = 0
    outs["__complete__"] = "yes"
    flush()
    if os.path.exists(sent_path):
        os.unlink(sent_path)


def check_consistency(report):
    """Replay the op sweep's forward SPECS on TPU vs CPU (the reference's
    cpu/gpu check_consistency tier, test_utils.py:1207). The CPU
    reference leg runs in a JAX_PLATFORMS=cpu child interpreter; the TPU
    leg runs here (the axon process's only backend IS the TPU)."""
    spec_hash = _sweep_spec_hash()
    ref_path = os.path.join(ROOT, ".consistency_cpu_ref.pkl")
    cpu_ref = _load_leg_pickle(ref_path, spec_hash)  # cached across runs
    if cpu_ref is None or "__complete__" not in cpu_ref:
        if cpu_ref is None and os.path.exists(ref_path):
            os.unlink(ref_path)  # stale/corrupt cache: start over
        # the leg resumes from whatever the cache holds and no-ops when
        # already complete, so running it is always safe
        proc = None
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--consistency-leg", ref_path],
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
                capture_output=True, text=True, timeout=900)
        except subprocess.TimeoutExpired:
            pass  # partial cache kept; completeness checked below
        cpu_ref = _load_leg_pickle(ref_path, spec_hash)
        if cpu_ref is None or "__complete__" not in cpu_ref:
            raise RuntimeError(
                "cpu reference leg incomplete: %s"
                % ((proc.stderr if proc else "") or "timeout")[-300:])

    # surface the prior attempt's partial TPU progress in the report
    # BEFORE the (wedgeable) TPU leg runs: a killed attempt still ships
    # this via the parent's partial-merge, and a finished attempt
    # overwrites the same key (the merge cannot propagate deletions)
    tpu_path = os.path.join(ROOT, ".consistency_tpu_out.pkl")
    prior = _load_leg_pickle(tpu_path, spec_hash) or {}
    report["consistency"] = {
        "partial": True,
        "tpu_ops_so_far": len([k for k in prior
                               if k not in _CONSISTENCY_META])}
    _flush(report)

    _consistency_leg(tpu_path)
    tpu_out = _load_leg_pickle(tpu_path, spec_hash)

    mismatches, errors, checked = [], [], 0
    common = (set(cpu_ref) & set(tpu_out)) - set(_CONSISTENCY_META)
    for name in sorted(common):
        outs = {"cpu": cpu_ref[name], "tpu": tpu_out[name]}
        for devname in ("cpu", "tpu"):
            if isinstance(outs[devname], str):  # recorded error
                errors.append({"op": name, "dev": devname,
                               "error": outs[devname]})
                outs[devname] = None
        if outs.get("cpu") is None or outs.get("tpu") is None:
            continue
        checked += 1
        for i, (a, b) in enumerate(zip(outs["cpu"], outs["tpu"])):
            if a.shape != b.shape:
                # np.allclose would raise on non-broadcastable shapes —
                # and a shape divergence IS the bug this check hunts
                mismatches.append({"op": name, "out": i,
                                   "max_abs_diff": "shape %s vs %s"
                                   % (a.shape, b.shape)})
                continue
            if a.dtype.kind == "f":
                # fp32 tier on-chip can use bf16 matmul passes: loose tol
                if not np.allclose(a.astype(np.float64),
                                   b.astype(np.float64),
                                   rtol=2e-2, atol=2e-2):
                    diff = float(np.max(np.abs(
                        a.astype(np.float64) - b.astype(np.float64))))
                    mismatches.append({"op": name, "out": i,
                                       "max_abs_diff": diff})
            else:
                if not np.array_equal(a, b):
                    mismatches.append({"op": name, "out": i,
                                       "max_abs_diff": "int mismatch"})
    report["consistency"] = {
        "ops_checked": checked,
        "mismatches": mismatches,
        "errors": errors[:20],
        "n_errors": len(errors),
    }
    _flush(report)
    os.unlink(tpu_path)  # only after a fully-reported compare


STAGES = [
    # (name, fn, child timeout seconds) — ordered by information value so
    # a short relay window captures the most important numbers first.
    # Completed stages are skipped via stages_done, so this order only
    # matters for what remains; the long resumable consistency sweep
    # goes last so it cannot eat a short window.
    ("roofline", check_roofline, 600),
    ("bench", check_bench, 2700),
    ("inference", check_inference, 1800),
    ("bench_autolayout", check_bench_autolayout, 1800),
    ("transformer_train", check_transformer_train, 1800),
    ("bench_nhwc", check_bench_nhwc, 1500),
    ("bench_scale", check_bench_scale, 2700),
    ("profile", check_profile, 1200),
    ("io_pipeline", check_io_pipeline, 1800),
    ("pallas_rnn", check_pallas_rnn, 1200),
    ("flash_attention", check_flash_attention, 1800),
    ("bench_smallbatch", check_bench_smallbatch, 2700),
    ("inference_smallbatch", check_inference_smallbatch, 1800),
    ("consistency", check_consistency, 1800),
]


def _load_report():
    if os.path.exists(REPORT):
        try:
            with open(REPORT) as f:
                return json.load(f)
        except Exception:
            pass
    return {}


def _run_stage_child(name, timeout):
    """Run one stage in a bounded subprocess; merge whatever it managed to
    write. The relay wedges mid-compile without erroring, so an unbounded
    in-process stage can block forever — a killed child only loses the
    variant in flight, not the window."""
    out_path = os.path.join(ROOT, ".tpu_stage_%s.json" % name)
    if os.path.exists(out_path):
        os.unlink(out_path)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--stage", name, "--out", out_path],
            timeout=timeout, capture_output=True, text=True)
        ok = proc.returncode == 0
        err = proc.stderr[-500:] if not ok else None
    except subprocess.TimeoutExpired:
        ok, err = False, "stage timeout after %ds" % timeout
    partial = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                partial = json.load(f)
        except Exception:
            pass  # child killed mid-dump: keep what the report already has
        finally:
            os.unlink(out_path)
    return ok, err, partial


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip", nargs="*", default=[],
                    choices=[s[0] for s in STAGES])
    ap.add_argument("--wait", type=int, default=0, metavar="MINUTES",
                    help="keep polling the relay up to this long, "
                         "resuming unfinished stages whenever it answers "
                         "(the relay wedges for hours at a time)")
    ap.add_argument("--stage", help="internal: run one stage in-process")
    ap.add_argument("--out", help="internal: stage output path")
    ap.add_argument("--consistency-leg", metavar="OUT_PKL",
                    help="internal: dump this backend's sweep outputs")
    args = ap.parse_args()

    if args.consistency_leg:
        _consistency_leg(args.consistency_leg)
        return 0

    if args.stage:
        # child mode: trust the parent's probe, run one stage, flush into
        # --out (partial results survive a timeout kill via _flush)
        fn = dict((n, f) for n, f, _t in STAGES)[args.stage]
        report = _load_report()
        report["_out_path"] = args.out

        def flush_to_out(rep, path=None):
            rep = {k: v for k, v in rep.items() if k != "_out_path"}
            with open(args.out, "w") as f:
                json.dump(rep, f, indent=2)
        globals()["_flush"] = flush_to_out
        fn(report)
        flush_to_out(report)
        return 0

    from bench import probe_backend
    deadline = time.time() + args.wait * 60
    report = _load_report()
    pending = [s for s in STAGES
               if s[0] not in args.skip
               and s[0] not in report.get("stages_done", [])]
    attempts = {}
    while pending:
        platform, kind = probe_backend()
        if platform == "tpu":
            report["device_kind"] = kind
            report["timestamp"] = time.strftime("%F %T")
            name, fn, timeout = pending[0]
            print("== %s ==" % name, flush=True)
            ok, err, partial = _run_stage_child(name, timeout)
            report.update(partial)
            if ok:
                report.setdefault("stages_done", []).append(name)
                report.pop(name + "_error", None)
                report.pop("tpu_unavailable", None)
                pending.pop(0)
            else:
                attempts[name] = attempts.get(name, 0) + 1
                report[name + "_error"] = err
                if attempts[name] >= 3:
                    print("stage %s failed 3x; skipping" % name,
                          flush=True)
                    pending.pop(0)
            _flush(report)
            continue
        pinned = os.environ.get("JAX_PLATFORMS", "")
        pinned_off_tpu = pinned and "tpu" not in pinned.lower()
        if platform is not None and (args.wait == 0 or pinned_off_tpu):
            # definitive: one-shot mode on a healthy non-TPU backend, or
            # the environment itself pins a non-TPU platform — waiting
            # could never succeed
            report["tpu_unavailable"] = True
            _flush(report)
            print(json.dumps(report)[:400])
            return 1
        if time.time() >= deadline:
            break
        remaining = int((deadline - time.time()) / 60)
        if platform is not None:
            # the relay errored FAST this probe (jax fell back to a
            # healthy cpu backend) instead of hanging — still a down
            # relay, and it can recover: keep waiting
            print("[%s] relay errored (probe fell back to %r); retrying "
                  "for up to %d more minutes"
                  % (time.strftime("%F %T"), platform, remaining),
                  flush=True)
        else:
            print("[%s] relay down; retrying for up to %d more minutes"
                  % (time.strftime("%F %T"), remaining), flush=True)
        time.sleep(min(900, max(60, deadline - time.time())))

    if pending:
        report["tpu_unavailable"] = True
    _flush(report)
    print(json.dumps(report, indent=2)[:2000])
    return 0 if not pending else 1


if __name__ == "__main__":
    sys.exit(main())
