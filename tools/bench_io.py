#!/usr/bin/env python
"""ImageNet-scale IO proof: measure ImageRecordIter decode+augment
throughput and decode/train overlap.

Reference methodology: the reference's ImageRecordIter v2 sustains
ImageNet training via an OMP parallel decode loop
(src/io/iter_image_recordio_2.cc:138-149). Here the fast path is a
spawned process pool (mxtpu/_image_worker.py); this script:

1. generates a synthetic JPEG dataset and packs it into multi-shard
   recordio files with tools/im2rec.py (the reference tool flow);
2. measures img/s through mx.io.ImageRecordIter for the legacy threaded
   path and the process-pool path at several worker counts;
3. demonstrates prefetch overlap: iterating while a synthetic training
   step consumes batches costs ~max(io, train), not their sum.

Run: JAX_PLATFORMS=cpu python tools/bench_io.py [--images N]
Numbers land in docs/io_performance.md (run on the same class of host
CPU the TPU VM provides).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def gen_dataset(root, n_images, size=360, n_shards=4):
    """Synthetic JPEGs (structured so they compress like photos) packed
    into n_shards recordio shards via im2rec."""
    from PIL import Image
    img_dir = os.path.join(root, "img")
    os.makedirs(img_dir, exist_ok=True)
    rng = np.random.RandomState(0)
    lst_rows = []
    for i in range(n_images):
        # smooth gradient + noise: realistic JPEG entropy
        yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
        base = (np.stack([xx, yy, (xx + yy) / 2], -1) / size * 255)
        base += rng.uniform(0, 40, (size, size, 3))
        base = np.clip(base + rng.uniform(-20, 20, 3), 0, 255)
        rel = "img_%04d.jpg" % i
        Image.fromarray(base.astype(np.uint8)).save(
            os.path.join(img_dir, rel), quality=85)
        lst_rows.append((i, i % 10, rel))
    shards = []
    for s in range(n_shards):
        lst = os.path.join(root, "part%d.lst" % s)
        with open(lst, "w") as f:
            for (idx, lab, rel) in lst_rows[s::n_shards]:
                f.write("%d\t%d\t%s\n" % (idx, lab, rel))
        prefix = os.path.join(root, "part%d" % s)
        subprocess.run([sys.executable,
                        os.path.join(os.path.dirname(__file__), "im2rec.py"),
                        prefix, img_dir + "/"],
                       check=True, capture_output=True,
                       env=dict(os.environ, JAX_PLATFORMS="cpu"))
        shards.append(prefix + ".rec")
    return shards


def measure_iter(make_iter, n_batches, batch_size):
    it = make_iter()
    next(iter(it))  # warm the pipeline/pool
    t0 = time.perf_counter()
    count = 0
    it.reset()
    for i, batch in enumerate(it):
        count += batch_size - (batch.pad or 0)
        if i + 1 >= n_batches:
            break
    dt = time.perf_counter() - t0
    if hasattr(it, "close"):
        it.close()
    return count / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=800)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--batches", type=int, default=10)
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    import mxtpu as mx

    root = tempfile.mkdtemp(prefix="mxtpu_io_bench_")
    print("generating %d jpegs + 4 recordio shards under %s ..."
          % (args.images, root))
    shards = gen_dataset(root, args.images)
    rec = shards[0]

    results = {"cpu_count": os.cpu_count()}

    # single-core decode+augment cost (the scaling unit: pool throughput
    # ~= workers / cost once cores back the workers)
    from mxtpu import _image_worker
    from mxtpu.image import _read_record_items, _FastRecordIter
    items = _read_record_items(rec)
    cfg = {"crop_h": 224, "crop_w": 224, "resize": 256, "rand_crop": True,
           "rand_mirror": True,
           "mean": np.array([123.68, 116.78, 103.94], np.float32),
           "std": np.array([58.4, 57.1, 57.4], np.float32)}
    wcfg = dict(cfg, mean=None, std=None)
    _image_worker.init_worker(wcfg)
    t0 = time.perf_counter()
    for i in range(min(100, len(items))):
        _image_worker.decode_augment((i, items[i][0], 0.0))
    per_img = (time.perf_counter() - t0) / min(100, len(items))
    results["decode_augment_ms_per_img"] = round(per_img * 1e3, 2)
    results["projected_img_s_at_8_workers"] = round(8 / per_img, 1)

    common = dict(path_imgrec=rec, data_shape=(3, 224, 224),
                  batch_size=args.batch_size, shuffle=True, rand_crop=True,
                  rand_mirror=True, mean_r=123.68, mean_g=116.78,
                  mean_b=103.94, std_r=58.4, std_g=57.1, std_b=57.4,
                  resize=256)

    # in-process path (thread prefetch only; what ImageRecordIter picks on
    # single-core hosts)
    legacy = measure_iter(
        lambda: mx.io.ImageRecordIter(preprocess_threads=1, **common),
        args.batches, args.batch_size)
    results["inprocess_thread_prefetch"] = round(legacy, 1)

    # process-pool path, constructed directly so it is measured even on a
    # single-core host (ImageRecordIter only selects it with >1 cores)
    def make_pool_iter(n):
        return _FastRecordIter(items, args.batch_size, (3, 224, 224), cfg,
                               True, n, 4, "data", "softmax_label")

    worker_counts = [1, 2, 4, 8] if (os.cpu_count() or 1) > 1 else [2]
    for nproc in worker_counts:
        r = measure_iter(lambda n=nproc: make_pool_iter(n),
                         args.batches, args.batch_size)
        results["procpool_%d" % nproc] = round(r, 1)

    # overlap demo: consume batches while a synthetic 25ms training step
    # runs per batch; perfect overlap => wall ~= max(io, 25ms)*batches
    def overlapped(make_iter):
        it = make_iter()
        next(iter(it))
        it.reset()
        t0 = time.perf_counter()
        n = 0
        for i, batch in enumerate(it):
            time.sleep(0.025)       # stand-in training step
            n += args.batch_size
            if i + 1 >= args.batches:
                break
        dt = time.perf_counter() - t0
        if hasattr(it, "close"):
            it.close()
        return n / dt

    results["pool_with_25ms_step"] = round(
        overlapped(lambda: make_pool_iter(max(worker_counts))), 1)
    results["multi_shard"] = [os.path.basename(s) for s in shards]
    print(json.dumps(results, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
