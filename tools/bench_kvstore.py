#!/usr/bin/env python
"""Loopback microbench for the dist_async comms fast path.

Measures the two regimes the wire rework targets (ISSUE 2; the numbers
land in docs/perf_analysis.md "Comms fast path" and the before/after
ratio is the acceptance criterion), on BOTH transports:

* **bigarray push/pull** — one --mb MB gradient (default 64, split into
  row parts at MXTPU_KVSTORE_BIGARRAY_BOUND) pushed/pulled --iters
  times: MB/s plus p50/p99 per-call latency.
* **small-key ops/s** — --small-keys keys of --small-bytes each (default
  256 x 1 KB, the embedding/bias tail of a real model) pushed/pulled as
  one list call per iteration: ops/s. This is the regime where
  multi-key coalescing (MXTPU_PS_COALESCE_BYTES) pays.

The headline numbers are the default transport — the same-process
shortcut (MXTPU_PS_LOCAL), since the bench's server is in-process, the
same situation as single-process dist_async mode. The "tcp" sub-object
repeats the measurement with the shortcut disabled, i.e. over real
loopback framing: zero-copy scatter-gather sends, recv_into receives,
the MXTPU_PS_WINDOW pipelined window and coalesced frames.

Prints exactly ONE JSON line (tests/test_bench_contract.py parses it)
and mirrors it to docs/kvstore_bench.json unless --no-write. CPU-only,
in-process loopback server — runnable every round with no TPU.

Run: JAX_PLATFORMS=cpu python tools/bench_kvstore.py [--mb 64]
     [--small-keys 256] [--iters 5]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, ROOT)


def _pct(samples, q):
    s = sorted(samples)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def _lat(samples_s):
    return {"p50_ms": round(_pct(samples_s, 0.50) * 1e3, 3),
            "p99_ms": round(_pct(samples_s, 0.99) * 1e3, 3)}


def _measure(kv, mx, mb, small_keys, small_bytes, iters, tag):
    """One full push/pull measurement pass on an open store."""
    # -- bigarray regime --------------------------------------------
    elems = int(mb * 1e6 / 4)
    rows = max(1, elems // 4608)
    big = mx.nd.array(np.random.RandomState(0)
                      .rand(rows, 4608).astype("f"))
    out = mx.nd.zeros(big.shape)
    payload_mb = big.size * 4 / 1e6
    key = "big_" + tag                 # fresh keys per pass: no clock
    kv.init(key, big)                  # interference across transports
    kv.push(key, big)                  # warm plans/sockets/jit
    kv.pull(key, out=out)

    push_t, pull_t = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        kv.push(key, big)
        push_t.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        kv.pull(key, out=out)
        pull_t.append(time.perf_counter() - t0)

    # -- small-key regime -------------------------------------------
    n_small_elems = max(1, small_bytes // 4)
    keys = ["%s_s%03d" % (tag, i) for i in range(small_keys)]
    vals = [mx.nd.array(np.full(n_small_elems, float(i % 7), "f"))
            for i in range(small_keys)]
    outs = [mx.nd.zeros((n_small_elems,)) for _ in keys]
    kv.init(keys, vals)
    kv.push(keys, vals)                # warm
    kv.pull(keys, out=outs)

    t0 = time.perf_counter()
    for _ in range(iters):
        kv.push(keys, vals)
    small_push_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        kv.pull(keys, out=outs)
    small_pull_s = time.perf_counter() - t0

    return {
        "payload_mb": round(payload_mb, 1),
        "push_mb_s": round(payload_mb / (sum(push_t) / iters), 1),
        "pull_mb_s": round(payload_mb / (sum(pull_t) / iters), 1),
        "push": _lat(push_t),
        "pull": _lat(pull_t),
        "small_push_ops_s": round(small_keys * iters / small_push_s),
        "small_pull_ops_s": round(small_keys * iters / small_pull_s),
    }


def run(mb, small_keys, small_bytes, iters):
    import mxtpu as mx
    from mxtpu import kvstore_async as ka

    srv = ka.ParameterServer().start()
    saved = os.environ.get("MXTPU_PS_ADDRS")
    os.environ["MXTPU_PS_ADDRS"] = srv.address
    local_saved = ka._LOCAL_ON
    try:
        kv = mx.kv.create("dist_async")

        # default transport first (the same-process shortcut when it is
        # on), then the wire with the shortcut pinned off
        head = _measure(kv, mx, mb, small_keys, small_bytes, iters,
                        "loc" if local_saved else "tcp")
        tcp = head
        if local_saved:
            ka._LOCAL_ON = False
            tcp = _measure(kv, mx, mb, small_keys, small_bytes, iters,
                           "tcp")
            ka._LOCAL_ON = local_saved

        n_parts = sum(len(p) for p in kv._parts.values())
        result = {
            "bench": "kvstore_loopback",
            "transport": "local" if local_saved else "tcp",
            "n_parts": n_parts,
            "iters": iters,
            "small_keys": small_keys,
            "small_bytes": small_bytes,
            "window": int(os.environ.get("MXTPU_PS_WINDOW", "8") or 0),
            "host_cores": os.cpu_count(),
        }
        result.update(head)
        result["tcp"] = {k: tcp[k] for k in
                         ("push_mb_s", "pull_mb_s", "push", "pull",
                          "small_push_ops_s", "small_pull_ops_s")}
        s = kv.stats()                 # comms counters (fast-path proof)
        result["wire"] = {k: s[k] for k in
                          ("bytes_sent", "bytes_recv", "frames_sent",
                           "frames_recv", "coalesced_subs", "local_reqs",
                           "inflight_hwm", "retransmits")
                          if k in s}
        kv.close()
        return result
    finally:
        ka._LOCAL_ON = local_saved
        if saved is None:
            os.environ.pop("MXTPU_PS_ADDRS", None)
        else:
            os.environ["MXTPU_PS_ADDRS"] = saved
        srv.stop()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=float, default=64.0,
                    help="bigarray gradient volume in MB")
    ap.add_argument("--small-keys", type=int, default=256)
    ap.add_argument("--small-bytes", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--no-write", action="store_true",
                    help="do not mirror the line to docs/kvstore_bench.json")
    args = ap.parse_args()

    result = run(args.mb, args.small_keys, args.small_bytes, args.iters)
    line = json.dumps(result)
    print(line, flush=True)
    if not args.no_write:
        with open(os.path.join(ROOT, "docs", "kvstore_bench.json"),
                  "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
