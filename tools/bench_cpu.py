#!/usr/bin/env python
"""Relay-independent CPU inference scoreboard.

The reference publishes CPU inference throughput for the model zoo
(``docs/faq/perf.md:31-90``), measured with
``example/image-classification/benchmark_score.py`` on AWS C4 instances
— e.g. C4.8xlarge (36 vCPUs): ResNet-50 batch-32 = 62.19 img/s, VGG
87.15, Inception-v3 83.05, Alexnet 564.04. Those tables are reachable
every session, so this scoreboard produces a measured comparison against
reference numbers no matter what the TPU relay is doing.

Methodology matches the reference script (fixed synthetic batch, forward
only, steady-state timing after a warmup) via the same
``benchmark_score.score`` entry the TPU inference stage uses. The
honesty knob is core count: this host exposes few cores while the
reference tables are 36/8/4/2-vCPU machines, so the comparison is
reported per-vCPU alongside the raw rates, with the closest-size C4
row quoted too. Per-vCPU normalization is imperfect (vCPUs are
hyperthreads; small instances turbo higher per core) — both raw and
normalized numbers are recorded so the reader can apply either.

Writes docs/cpu_scoreboard.json. bench.py's CPU fallback reuses
``score_resnet50_cpu`` so a relay-down round still emits a number with a
defensible ``vs_baseline`` instead of a toy-shape throughput.

Run: JAX_PLATFORMS=cpu python tools/bench_cpu.py [--quick]
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, ROOT)

# reference perf.md:40-47 (C4.8xlarge, 36 vCPU) and :78-85 (C4.xlarge,
# 4 vCPU), batch 32 and batch 1 rows
C4_8XL_VCPUS = 36
C4_8XL_B32 = {"alexnet": 564.04, "vgg16": 87.15, "inception-v3": 83.05,
              "resnet-50": 62.19, "resnet-152": 25.76,
              "inception-bn": 208.21}
C4_8XL_B1 = {"alexnet": 119.57, "vgg16": 34.23, "inception-v3": 54.42,
             "resnet-50": 42.83, "resnet-152": 19.51,
             "inception-bn": 111.36}
C4_XL_VCPUS = 4
C4_XL_B32 = {"alexnet": 65.05, "vgg16": 10.91, "inception-v3": 9.34,
             "resnet-50": 10.31, "resnet-152": 3.86,
             "inception-bn": 33.86}
C4_XL_B1 = {"alexnet": 37.92, "vgg16": 6.57, "inception-v3": 8.79,
            "resnet-50": 9.65, "resnet-152": 3.73,
            "inception-bn": 23.09}


def _score_mod():
    spec = importlib.util.spec_from_file_location(
        "benchmark_score", os.path.join(
            ROOT, "example", "image-classification", "benchmark_score.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _adaptive_iters(one_iter_s, budget_s=30.0, lo=3, hi=20):
    return max(lo, min(hi, int(budget_s / max(one_iter_s, 1e-3))))


def score_model(name, batch=32, n_iter=None):
    """images/sec, reference methodology; iteration count auto-scales so
    slow models on small hosts still finish in a bounded time."""
    bs = _score_mod()
    hw = 299 if name == "inception-v3" else 224
    if n_iter is None:
        t0 = time.perf_counter()
        bs.score(name, batch, hw, n_iter=1)      # includes compile
        bs_one = time.perf_counter()
        one = bs.score(name, batch, hw, n_iter=1)
        del bs_one, one
        n_iter = _adaptive_iters((time.perf_counter() - t0) / 2)
    return bs.score(name, batch, hw, n_iter=n_iter)


def score_resnet50_cpu(n_iter=5):
    """The bench.py CPU-fallback number: ResNet-50 batch-32 forward,
    the exact row the reference publishes for every C4 size."""
    bs = _score_mod()
    return bs.score("resnet-50", 32, 224, n_iter=n_iter)


def score_tiny():
    """Contract-test shape (bench.py MXTPU_BENCH_TINY): the same scoring
    pipeline at toy size, finishing in seconds."""
    return _score_mod().score("resnet-50", 2, 32, n_iter=2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="resnet-50 only (the headline row)")
    ap.add_argument("--models", default=None,
                    help="comma-separated subset; merges into the "
                         "existing docs/cpu_scoreboard.json (for "
                         "re-measuring a row that ran contended)")
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    cores = len(os.sched_getaffinity(0))
    report = {
        "host_cpu": "unknown",
        "host_cores": cores,
        "batch": args.batch,
        "method": "benchmark_score.score, fwd-only, synthetic batch, "
                  "steady-state after warmup (reference perf.md "
                  "methodology); chained-input difference timing with "
                  "host-fetch sync (mxtpu/benchmarking.py, round 5)",
        "reference": {
            "c4.8xlarge_b32": C4_8XL_B32, "c4.8xlarge_b1": C4_8XL_B1,
            "c4.8xlarge_vcpus": C4_8XL_VCPUS,
            "c4.xlarge_b32": C4_XL_B32, "c4.xlarge_b1": C4_XL_B1,
            "c4.xlarge_vcpus": C4_XL_VCPUS,
            "source": "/root/reference/docs/faq/perf.md:31-90"},
        "timestamp": time.strftime("%F %T"),
    }
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    report["host_cpu"] = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass

    models = ["resnet-50"] if args.quick else \
        ["resnet-50", "vgg16", "inception-v3", "alexnet", "resnet-152",
         "inception-bn"]
    out = os.path.join(ROOT, "docs", "cpu_scoreboard.json")
    try:   # always merge: a batch-1 or single-model run must not clobber
        with open(out) as f:   # the other rows already measured
            results = json.load(f).get("results", {})
    except OSError:
        results = {}
    if args.models:
        models = [m.strip() for m in args.models.split(",") if m.strip()]
    tables = {32: (C4_8XL_B32, C4_XL_B32), 1: (C4_8XL_B1, C4_XL_B1)}
    t8, txl = tables.get(args.batch, ({}, {}))
    for name in models:
        img_s = score_model(name, args.batch)
        entry = {"img_per_sec": round(img_s, 2),
                 "per_core": round(img_s / cores, 2), "batch": args.batch}
        for label, table, vcpus in (("c4.8xlarge", t8, C4_8XL_VCPUS),
                                    ("c4.xlarge", txl, C4_XL_VCPUS)):
            ref = table.get(name)
            if ref:
                entry["vs_%s" % label] = round(img_s / ref, 3)
                entry["vs_%s_per_vcpu" % label] = round(
                    (img_s / cores) / (ref / vcpus), 2)
        key = name if args.batch == 32 else "%s@b%d" % (name, args.batch)
        results[key] = entry
        print(key, entry, flush=True)
    report["results"] = results

    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print("wrote", out)


if __name__ == "__main__":
    main()
