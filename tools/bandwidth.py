#!/usr/bin/env python
"""Collective-bandwidth diagnostic (reference tools/bandwidth/measure.py,
cited by docs/faq/perf.md:194-196 for weighing compute vs communication).

The reference measures KVStore push+pull bytes/sec across GPUs for a
given network's gradient sizes. Here the comm fabric is XLA collectives
over the device mesh, so we time psum (the gradient all-reduce),
all_gather (the weight broadcast analogue) and ppermute (the ring/
pipeline primitive) for a sweep of sizes, and per-network gradient
totals for the model-zoo names the reference script takes via --network.

Run on TPU hardware, or locally with
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python tools/bandwidth.py --sizes 1e6 --iters 5
"""
from __future__ import annotations

import argparse
import functools
import time


def measure(fn, x, iters):
    """Per-collective seconds, with dispatch/transfer overhead cancelled:
    time an iters-loop and a 2*iters-loop (both ending in the same scalar
    round-trip) and difference them, so the fixed cost of the final
    reduction + host sync drops out of the reported number."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames="n")
    def loop(x, n):
        def body(_, acc):
            return acc + fn(x)
        return jnp.sum(jax.lax.fori_loop(0, n, body, jnp.zeros_like(x)))

    float(loop(x, iters))                     # compile both variants
    float(loop(x, 2 * iters))

    def timed(n):
        best = float("inf")
        for _ in range(3):
            t = time.perf_counter()
            float(loop(x, n))
            best = min(best, time.perf_counter() - t)
        return best

    t_short, t_long = timed(iters), timed(2 * iters)
    if t_long > t_short:
        return (t_long - t_short) / iters
    return t_long / (2 * iters)               # jitter floor: raw estimate


def main():
    import os
    import jax
    # honor JAX_PLATFORMS even when a sitecustomize pre-set the platform
    # list at interpreter start (it overrides the env var otherwise)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    # standalone tool: jax-only shard_map compat (mxtpu may not be on
    # sys.path when invoked as a script; mirror parallel/mesh.py's shim)
    import inspect
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
    if "check_vma" in inspect.signature(_sm).parameters:
        shard_map = _sm
    else:
        def shard_map(*a, **kw):
            kw["check_rep"] = kw.pop("check_vma", True)
            return _sm(*a, **kw)
    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as np

    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", type=float, nargs="+",
                    default=[1e5, 1e6, 1e7],
                    help="elements (fp32) per collective")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--network", type=str, default=None,
                    help="model-zoo name: also report that net's total "
                         "gradient bytes per step")
    args = ap.parse_args()

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("x",))
    print("devices: %d x %s" % (n, devs[0].platform))

    def run(name, fn, size):
        x = jnp.ones((n, int(size)), jnp.float32)
        sm = shard_map(fn, mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                       check_vma=False)
        dt = measure(sm, x, args.iters)
        nbytes = int(size) * 4
        # ring all-reduce moves 2(n-1)/n of the payload per device
        print("%-12s %10d B  %8.3f ms  %8.2f GB/s (algo)"
              % (name, nbytes, dt * 1e3, nbytes / dt / 1e9))

    perm = [(i, (i + 1) % n) for i in range(n)]
    for size in args.sizes:
        run("psum", lambda v: jax.lax.psum(v, "x"), size)
        run("all_gather",
            lambda v: jax.lax.all_gather(v, "x").reshape(v.shape[0] * n,
                                                         -1)[:v.shape[0]],
            size)
        run("ppermute",
            functools.partial(jax.lax.ppermute, axis_name="x", perm=perm),
            size)

    if args.network:
        import mxtpu as mx
        from mxtpu.gluon.model_zoo import vision
        net = getattr(vision, args.network)()
        net.initialize(mx.init.Xavier())
        net(mx.nd.zeros((1, 3, 224, 224)))
        total = sum(int(np.prod(p.shape)) * 4
                    for p in net.collect_params().values())
        print("%s gradient payload per step: %.1f MB"
              % (args.network, total / 1e6))


if __name__ == "__main__":
    main()
