#!/usr/bin/env python
"""Caffe prototxt -> mxtpu symbol converter (reference
``tools/caffe_converter/convert_symbol.py`` + ``convert_model.py``).

The reference converter walks a caffe ``NetParameter`` and emits symbol
construction code for each layer. This version is self-contained: it
parses the protobuf *text* format directly (no caffe install needed) and
builds the symbol graph programmatically. Weight conversion from binary
``.caffemodel`` files requires the caffe protobuf schema and is gated on
``import caffe`` exactly like the reference (caffe_parser.py).

Supported layers (the set the reference's example conversions use):
Data/Input, Convolution, InnerProduct, ReLU, Pooling (MAX/AVE), LRN,
Dropout, BatchNorm(+Scale), Concat, Eltwise (SUM/MAX/PROD), Flatten,
Softmax/SoftmaxWithLoss, Accuracy (skipped).

CLI:  python tools/caffe_converter.py net.prototxt out-prefix
writes ``out-prefix-symbol.json``.
"""
from __future__ import annotations

import argparse
import re
import sys


# ---------------------------------------------------------------------------
# protobuf text-format parsing (minimal, schema-free)
# ---------------------------------------------------------------------------

def parse_prototxt(text):
    """Parse protobuf text format into nested dicts with repeated fields
    as lists (enough structure for NetParameter)."""
    text = re.sub(r"#[^\n]*", "", text)
    pos = 0
    n = len(text)

    def skip_ws(p):
        while p < n and text[p] in " \t\r\n,;":
            p += 1
        return p

    def parse_block(p):
        msg = {}
        while True:
            p = skip_ws(p)
            if p >= n or text[p] == "}":
                return msg, p + 1
            m = re.match(r"[A-Za-z_][A-Za-z0-9_]*", text[p:])
            if not m:
                raise ValueError("parse error near %r" % text[p:p + 40])
            key = m.group(0)
            p = skip_ws(p + m.end())
            if p < n and text[p] == ":":
                p = skip_ws(p + 1)
                if text[p] == '"':
                    e = text.index('"', p + 1)
                    val = text[p + 1:e]
                    p = e + 1
                else:
                    m2 = re.match(r"[^\s{},;]+", text[p:])
                    raw = m2.group(0)
                    p += m2.end()
                    if raw in ("true", "false"):
                        val = raw == "true"
                    else:
                        try:
                            val = int(raw)
                        except ValueError:
                            try:
                                val = float(raw)
                            except ValueError:
                                val = raw      # enum token
            elif p < n and text[p] == "{":
                val, p = parse_block(p + 1)
            else:
                raise ValueError("expected ':' or '{' after %r" % key)
            if key in msg:
                if not isinstance(msg[key], list):
                    msg[key] = [msg[key]]
                msg[key].append(val)
            else:
                msg[key] = val

    msg, _ = parse_block(0)
    return msg


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# ---------------------------------------------------------------------------
# layer translation
# ---------------------------------------------------------------------------

def _conv_args(param):
    """(kernel, stride, pad) as (h, w) pairs — caffe expresses each either
    as one square value or as separate *_h/*_w fields."""
    def pick(key, default=0):
        v = param.get(key, default)
        return int(_as_list(v)[0]) if _as_list(v) else default

    def pair(base, default):
        sq = pick(base if base != "kernel" else "kernel_size", default)
        h = pick(base + "_h", sq)
        w = pick(base + "_w", sq)
        return (h if h else default, w if w else default)

    return pair("kernel", 0), pair("stride", 1), pair("pad", 0)


def convert_symbol(prototxt_text):
    """Build (symbol, input_name) from a prototxt string (reference
    convert_symbol.py:proto2symbol)."""
    import mxtpu as mx

    net = parse_prototxt(prototxt_text)
    layers = _as_list(net.get("layer")) or _as_list(net.get("layers"))
    nodes = {}
    input_name = None
    sym = None

    for inp in _as_list(net.get("input")):
        nodes[inp] = mx.sym.var(inp)
        input_name = input_name or inp

    for layer in layers:
        ltype = str(layer.get("type", ""))
        name = layer.get("name", ltype)
        bottoms = [nodes[b] for b in _as_list(layer.get("bottom"))
                   if b in nodes]
        tops = _as_list(layer.get("top")) or [name]

        include = layer.get("include")
        if include and _as_list(include) and \
                str(_as_list(include)[0].get("phase", "")) == "TEST" and \
                ltype in ("Data", "Input", "ImageData"):
            continue

        if ltype in ("Data", "Input", "ImageData", "MemoryData", "HDF5Data"):
            sym = mx.sym.var("data")
            nodes["data"] = sym
            input_name = input_name or "data"
            for t in tops:
                nodes[t] = sym
            continue
        if not bottoms:
            continue
        x = bottoms[0]

        if ltype == "Convolution":
            p = layer.get("convolution_param", {})
            k, st, pad = _conv_args(p)
            sym = mx.sym.Convolution(
                x, name=name, num_filter=int(p.get("num_output", 1)),
                kernel=k, stride=st, pad=pad,
                num_group=int(p.get("group", 1)),
                no_bias=not p.get("bias_term", True))
        elif ltype == "InnerProduct":
            p = layer.get("inner_product_param", {})
            sym = mx.sym.FullyConnected(
                mx.sym.Flatten(x), name=name,
                num_hidden=int(p.get("num_output", 1)),
                no_bias=not p.get("bias_term", True))
        elif ltype == "ReLU":
            sym = mx.sym.Activation(x, name=name, act_type="relu")
        elif ltype == "TanH":
            sym = mx.sym.Activation(x, name=name, act_type="tanh")
        elif ltype == "Sigmoid":
            sym = mx.sym.Activation(x, name=name, act_type="sigmoid")
        elif ltype == "Pooling":
            p = layer.get("pooling_param", {})
            k, st, pad = _conv_args(p)
            pool = "max" if str(p.get("pool", "MAX")) == "MAX" else "avg"
            if p.get("global_pooling"):
                sym = mx.sym.Pooling(x, name=name, global_pool=True,
                                     kernel=(1, 1), pool_type=pool)
            else:
                sym = mx.sym.Pooling(x, name=name, kernel=k,
                                     stride=st, pad=pad,
                                     pool_type=pool,
                                     pooling_convention="full")
        elif ltype == "LRN":
            p = layer.get("lrn_param", {})
            sym = mx.sym.LRN(x, name=name,
                             alpha=float(p.get("alpha", 1e-4)),
                             beta=float(p.get("beta", 0.75)),
                             knorm=float(p.get("k", 2)),
                             nsize=int(p.get("local_size", 5)))
        elif ltype == "Dropout":
            p = layer.get("dropout_param", {})
            sym = mx.sym.Dropout(x, name=name,
                                 p=float(p.get("dropout_ratio", 0.5)))
        elif ltype == "BatchNorm":
            p = layer.get("batch_norm_param", {})
            # caffe BatchNorm has no affine terms; its paired Scale layer
            # carries gamma/beta. Our BatchNorm owns gamma/beta, so keep
            # gamma LEARNABLE (fix_gamma=False) and fold Scale to identity
            # — the converted net keeps the per-channel scale capacity
            # (reference convert_symbol.py emits fix_gamma=False too).
            sym = mx.sym.BatchNorm(
                x, name=name, fix_gamma=False,
                eps=float(p.get("eps", 1e-5)),
                use_global_stats=bool(p.get("use_global_stats", False)))
        elif ltype == "Scale":
            # affine absorbed by the preceding BatchNorm's gamma/beta
            sym = x
        elif ltype == "Concat":
            sym = mx.sym.Concat(*bottoms, name=name, dim=1)
        elif ltype == "Eltwise":
            p = layer.get("eltwise_param", {})
            op = str(p.get("operation", "SUM"))
            sym = bottoms[0]
            for b in bottoms[1:]:
                if op == "SUM":
                    sym = sym + b
                elif op == "PROD":
                    sym = sym * b
                else:
                    sym = mx.sym.maximum(sym, b)
        elif ltype == "Flatten":
            sym = mx.sym.Flatten(x, name=name)
        elif ltype in ("Softmax", "SoftmaxWithLoss"):
            # keep the layer's own name: multi-head nets (GoogLeNet aux
            # classifiers) must not collide on a hardcoded "softmax"
            sym = mx.sym.SoftmaxOutput(x, name=name)
        elif ltype in ("Accuracy", "Silence"):
            continue
        else:
            raise NotImplementedError(
                "caffe layer type %r not supported (reference "
                "convert_symbol.py covers the same core set)" % ltype)
        for t in tops:
            nodes[t] = sym

    if sym is None:
        raise ValueError("prototxt contains no convertible layers")
    return sym, input_name or "data"


def convert_model(prototxt_path, caffemodel_path, output_prefix):
    """Weight conversion is NOT implemented. The reference convert_model.py
    reads .caffemodel blobs through caffe's protobuf schema; without a
    caffe install to validate against, this build ships symbol conversion
    only. Porting weights: load the net in caffe, dump each blob to an
    .npz keyed by the symbol's parameter names, and save with
    mxtpu.nd.save — the symbol from :func:`convert_symbol` binds to it."""
    raise NotImplementedError(
        "caffemodel blob conversion is not implemented; use "
        "convert_symbol for the graph and port weights via numpy "
        "(see docstring)")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("prototxt")
    ap.add_argument("output_prefix")
    args = ap.parse_args(argv)
    with open(args.prototxt) as f:
        sym, _ = convert_symbol(f.read())
    path = args.output_prefix + "-symbol.json"
    sym.save(path)
    print("wrote %s" % path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
