#!/usr/bin/env python
"""im2rec: pack an image folder into RecordIO (reference tools/im2rec.py).

Two phases, same CLI shape as the reference:
  --list   walk a directory, write `prefix.lst` (index\\tlabel\\tpath);
  (default) read `prefix.lst`, encode images, write `prefix.rec` +
  `prefix.idx` for MXIndexedRecordIO random access.

Uses Pillow for decode/resize (the reference shells into OpenCV).
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxtpu import recordio  # noqa: E402

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(args):
    image_list = []
    label = 0
    label_of = {}
    for root, dirs, files in sorted(os.walk(args.root)):
        dirs.sort()
        files.sort()
        for f in files:
            if os.path.splitext(f)[1].lower() not in _EXTS:
                continue
            cat = os.path.relpath(root, args.root).split(os.sep)[0]
            if cat not in label_of:
                label_of[cat] = label
                label += 1
            image_list.append((label_of[cat],
                               os.path.relpath(os.path.join(root, f),
                                               args.root)))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    n = len(image_list)
    chunk = n // args.chunks
    for c in range(args.chunks):
        suffix = "" if args.chunks == 1 else "_%d" % c
        part = image_list[c * chunk:(c + 1) * chunk
                          if c < args.chunks - 1 else n]
        n_train = int(len(part) * args.train_ratio)
        sets = [("train" if args.train_ratio < 1 else "", part[:n_train])]
        if args.train_ratio < 1:
            sets.append(("val", part[n_train:]))
        for setname, items in sets:
            name = args.prefix + suffix + \
                ("_" + setname if setname else "") + ".lst"
            with open(name, "w") as f:
                for i, (lab, path) in enumerate(items):
                    f.write("%d\t%f\t%s\n" % (i, lab, path))
            print("wrote %s (%d items)" % (name, len(items)))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), float(parts[1]), parts[-1]


def im2rec(args):
    try:
        from PIL import Image
    except ImportError:
        sys.exit("im2rec needs Pillow for image encoding")
    lst = args.prefix + ".lst"
    rec = recordio.MXIndexedRecordIO(args.prefix + ".idx",
                                     args.prefix + ".rec", "w")
    count = 0
    for idx, label, path in read_list(lst):
        full = os.path.join(args.root, path)
        try:
            img = Image.open(full).convert("RGB")
        except Exception as e:  # noqa: BLE001
            print("skipping %s: %s" % (path, e))
            continue
        if args.resize:
            w, h = img.size
            scale = args.resize / min(w, h)
            img = img.resize((max(1, int(w * scale)),
                              max(1, int(h * scale))))
        if args.center_crop:
            w, h = img.size
            s = min(w, h)
            img = img.crop(((w - s) // 2, (h - s) // 2,
                            (w + s) // 2, (h + s) // 2))
        import io as _io
        buf = _io.BytesIO()
        img.save(buf, format="JPEG", quality=args.quality)
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack(header, buf.getvalue()))
        count += 1
    rec.close()
    print("packed %d images into %s.rec" % (count, args.prefix))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix", help="output prefix (and .lst path)")
    p.add_argument("root", help="image root directory")
    p.add_argument("--list", action="store_true",
                   help="generate the .lst file instead of packing")
    p.add_argument("--no-shuffle", dest="shuffle", action="store_false",
                   help="keep list order (default shuffles with seed 100)")
    p.add_argument("--chunks", type=int, default=1)
    p.add_argument("--train-ratio", type=float, default=1.0)
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--center-crop", action="store_true")
    p.add_argument("--quality", type=int, default=95)
    args = p.parse_args()
    if args.list:
        make_list(args)
    else:
        im2rec(args)


if __name__ == "__main__":
    main()
