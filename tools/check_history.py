#!/usr/bin/env python
"""Offline consistency checker CLI (ISSUE 19 tentpole c).

Point it at a journal directory written under ``MXTPU_HISTORY_DIR``
and it proves — or disproves — the four replication guarantees over
the recorded history: no acked write lost, no double apply,
single-writer-per-epoch, monotone per-key clocks.

    python tools/check_history.py /tmp/drill_history
    python tools/check_history.py --json /tmp/drill_history

Exit 0 = history is clean; 1 = at least one proven violation;
2 = usage / empty history. Every partition drill ends here.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxtpu.devtools import consistency          # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="check a journaled dist_async history for "
                    "lost acks, double applies, split-brain writers "
                    "and clock regressions")
    ap.add_argument("history_dir", help="directory of history-*.jsonl "
                                        "files (MXTPU_HISTORY_DIR)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw report dict as JSON")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.history_dir):
        print("check_history: %r is not a directory" % args.history_dir,
              file=sys.stderr)
        return 2
    report = consistency.check(args.history_dir)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(consistency.format_report(report))
    if report["ops"] == 0:
        print("check_history: empty history (nothing was journaled — "
              "was MXTPU_HISTORY_DIR set for the drill?)",
              file=sys.stderr)
        return 2
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
