#!/usr/bin/env python
"""Distributed job launcher (reference tools/launch.py:29-111).

The reference shells into dmlc-tracker to spawn ps-lite scheduler/server/
worker processes over ssh/mpi/sge/yarn or locally. mxtpu's distributed
backend is ``jax.distributed`` (single controller per host, collectives
over ICI/DCN), so the launcher's job is to start N worker processes with
the coordinator environment — the `--launcher local` mode forks them on
this host (how the reference's nightly dist tests run without a cluster,
tests/nightly/dist_sync_kvstore.py), and `--launcher ssh` prints/execs
the per-host commands.

Env handed to each worker (read by mxtpu.kvstore / jax.distributed):
  MXTPU_COORDINATOR  host:port of process 0
  MXTPU_NUM_PROCS    world size
  MXTPU_PROC_ID      rank
(Plus DMLC_* aliases for scripts written against the reference.)
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


def launch_local(args, command):
    procs = []
    base_env = dict(os.environ)
    coordinator = "127.0.0.1:%d" % args.port
    for rank in range(args.num_workers):
        env = dict(base_env)
        env.update({
            "MXTPU_COORDINATOR": coordinator,
            "MXTPU_NUM_PROCS": str(args.num_workers),
            "MXTPU_PROC_ID": str(rank),
            # reference-compatible aliases
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_NUM_SERVER": str(args.num_servers),
            "DMLC_WORKER_ID": str(rank),
        })
        procs.append(subprocess.Popen(command, shell=True, env=env))
    code = 0
    try:
        for p in procs:
            p.wait()
            code = code or p.returncode
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        code = 1
    return code


def launch_ssh(args, command):
    hosts = [h.strip() for h in open(args.hostfile) if h.strip()]
    coordinator = "%s:%d" % (hosts[0], args.port)
    procs = []
    for rank in range(args.num_workers):
        host = hosts[rank % len(hosts)]
        envs = ("MXTPU_COORDINATOR=%s MXTPU_NUM_PROCS=%d MXTPU_PROC_ID=%d "
                "DMLC_ROLE=worker DMLC_NUM_WORKER=%d DMLC_NUM_SERVER=%d "
                "DMLC_WORKER_ID=%d"
                % (coordinator, args.num_workers, rank, args.num_workers,
                   args.num_servers, rank))
        remote = "ssh -o StrictHostKeyChecking=no %s 'cd %s && %s %s'" % (
            host, os.getcwd(), envs, command)
        print(remote)
        if not args.dry_run:
            procs.append(subprocess.Popen(remote, shell=True))
    code = 0
    for p in procs:
        p.wait()
        code = code or p.returncode
    return code


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("-s", "--num-servers", type=int, default=0,
                   help="accepted for reference-CLI parity; mxtpu has no "
                        "parameter servers (SPMD collectives instead)")
    p.add_argument("--launcher", choices=("local", "ssh"), default="local")
    p.add_argument("-H", "--hostfile", default=None)
    p.add_argument("--port", type=int, default=9327)
    p.add_argument("--dry-run", action="store_true")
    p.add_argument("command", nargs="+")
    args = p.parse_args()
    command = " ".join(args.command)
    if args.launcher == "local":
        sys.exit(launch_local(args, command))
    if not args.hostfile:
        sys.exit("ssh launcher requires --hostfile")
    sys.exit(launch_ssh(args, command))


if __name__ == "__main__":
    main()
