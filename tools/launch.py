#!/usr/bin/env python
"""Distributed job launcher (reference tools/launch.py:29-111).

The reference shells into dmlc-tracker to spawn ps-lite scheduler/server/
worker processes over ssh/mpi/sge/yarn or locally. mxtpu's distributed
backend is ``jax.distributed`` (single controller per host, collectives
over ICI/DCN), so the launcher's job is to start N worker processes with
the coordinator environment — the `--launcher local` mode forks them on
this host (how the reference's nightly dist tests run without a cluster,
tests/nightly/dist_sync_kvstore.py), and `--launcher ssh` prints/execs
the per-host commands.

Env handed to each worker (read by mxtpu.kvstore / jax.distributed):
  MXTPU_COORDINATOR  host:port of process 0
  MXTPU_NUM_PROCS    world size
  MXTPU_PROC_ID      rank
(Plus DMLC_* aliases for scripts written against the reference.)
"""
from __future__ import annotations

import argparse
import os
import secrets
import signal
import subprocess
import sys
import tempfile
import threading
import time

# the autoscale/scale actuation layer imports mxtpu.fleet (stdlib-only
# modules, but the package import needs the repo root on the path when
# the launcher runs from elsewhere)
ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def _reap(procs, grace=5.0):
    """Terminate-and-reap with escalation: SIGTERM every live child,
    give the fleet ``grace`` seconds to exit, SIGKILL stragglers, then
    collect every corpse — bounded at each stage, so the launcher can
    never hang on (or zombie-leak) a child that ignores TERM."""
    live = [p for p in procs if p.poll() is None]
    for p in live:
        try:
            p.send_signal(signal.SIGTERM)
        except OSError:
            pass
    deadline = time.time() + grace
    for p in live:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            try:
                p.kill()
            except OSError:
                pass
    for p in live:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:   # unkillable (D-state): log
            print("warning: pid %d did not die after SIGKILL" % p.pid,
                  file=sys.stderr)


def _free_port(preferred):
    """preferred if bindable, else an OS-assigned free port — a silent
    EADDRINUSE in a server child would surface only as late
    connection-refused errors in whatever workers hash to it."""
    import socket
    for port in (preferred, 0):
        try:
            with socket.socket() as s:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", port))
                return s.getsockname()[1]
        except OSError:
            continue
    return preferred


def _spawn_server(name, ps_port, base_env, args, role="primary",
                  peer=None):
    """One async parameter-server child. With a snapshot dir configured,
    the server snapshots its table there and a RESPAWN of the same slot
    restores it — kvstore_async auto-resume — because the respawn reuses
    the same port (workers reconnect via their retry layer) and the same
    per-slot directory. With --ps-replicas 2 each shard is a
    primary/backup pair: MXTPU_PS_PEER/MXTPU_PS_ROLE wire the pair
    together, and a respawned process re-negotiates its role at boot
    (a respawned ex-primary finds its promoted peer and rejoins as the
    new backup, catching up via state transfer)."""
    env = dict(base_env, DMLC_ROLE="server",
               MXTPU_PS_PORT=str(ps_port), JAX_PLATFORMS="cpu",
               MXTPU_PS_ROLE=role)
    if peer:
        env["MXTPU_PS_PEER"] = peer
    if args.ps_snapshot_dir:
        env["MXTPU_PS_SNAPSHOT_DIR"] = os.path.join(
            args.ps_snapshot_dir, "server_%s" % name)
        env["MXTPU_PS_SNAPSHOT_EVERY"] = str(args.ps_snapshot_every)
    proc = subprocess.Popen(
        [sys.executable, "-m", "mxtpu.kvstore_async"], env=env)
    # pid + port on stdout: external failover drills (and the E2E
    # parity test) kill -9 an exact server process by parsing this
    print("ps server %s role=%s pid=%d port=%d"
          % (name, role, proc.pid, ps_port), flush=True)
    return proc


def _spawn_serving_replica(idx, port, addrs, base_env, args):
    """One model-serving replica child (``python -m mxtpu.serving``).
    Every replica gets the FULL replica set in MXTPU_SERVE_ADDRS so its
    hello replies teach clients where to fail over. With a weight
    source configured (``--serve-weight-dir`` / ``--serve-weight-kv``)
    the replica catches up to the CURRENT weight version before it
    admits and then follows the stream live — which is also what makes
    a ``--serve-respawn`` rejoin well-defined: the revived process
    re-binds its port, catches up, re-hellos, and serves current
    weights. Replicas are reaped with the same ``_reap`` TERM→KILL
    escalation as servers — SIGTERM is their graceful drain (stop
    admissions, flush in-flight batches, exit 0), so a clean launcher
    exit never drops admitted requests."""
    env = dict(base_env, JAX_PLATFORMS="cpu",
               MXTPU_SERVE_PORT=str(port),
               MXTPU_SERVE_ADDRS=",".join(addrs),
               MXTPU_SERVE_MODEL=args.serve_model,
               MXTPU_SERVE_EPOCH=str(args.serve_epoch),
               MXTPU_SERVE_DATA_SHAPES=args.serve_data_shapes)
    if args.serve_buckets:
        env["MXTPU_SERVE_BUCKETS"] = args.serve_buckets
    if args.serve_weight_dir:
        env["MXTPU_SERVE_WEIGHT_DIR"] = args.serve_weight_dir
    if args.serve_weight_kv:
        env["MXTPU_SERVE_WEIGHT_KV"] = args.serve_weight_kv
    if args.serve_weight_poll is not None:
        env["MXTPU_SERVE_WEIGHT_POLL"] = str(args.serve_weight_poll)
    env.pop("DMLC_ROLE", None)     # not a parameter-server role process
    env["MXTPU_OBS_ROLE"] = "serving"   # telemetry role label
    proc = subprocess.Popen(
        [sys.executable, "-m", "mxtpu.serving"], env=env)
    # pid + port on stdout: kill -9 failover drills parse this, exactly
    # like the ps-server line
    print("serve replica %d pid=%d port=%d" % (idx, proc.pid, port),
          flush=True)
    return proc


def _parse_scale(spec):
    """``--scale`` drill events: ``;``-separated, each a comma list of
    ``key=value`` — ``after=SECONDS`` or ``at_step=N`` (needs
    ``--scale-progress``) picks the trigger, ``action=`` one of
    add_worker / remove_worker / split_shard, plus ``rank=`` (remove)
    and ``src=`` (split source server slot, default 0)."""
    events = []
    for item in spec.split(";"):
        item = item.strip()
        if not item:
            continue
        ev = {}
        for pair in item.split(","):
            k, _, v = pair.partition("=")
            ev[k.strip()] = v.strip()
        if ev.get("action") not in ("add_worker", "remove_worker",
                                    "split_shard", "add_replica",
                                    "drain_replica"):
            raise SystemExit("scale event %r needs action=add_worker|"
                             "remove_worker|split_shard|add_replica|"
                             "drain_replica" % item)
        if "after" not in ev and "at_step" not in ev:
            raise SystemExit("scale event %r needs after= or at_step="
                             % item)
        events.append(ev)
    return events


def _parse_rollout(spec):
    """``--rollout`` drill events: ``;``-separated, each a comma list
    of ``key=value`` — ``after=SECONDS`` or ``at_step=N`` (needs
    ``--scale-progress``) picks the trigger, ``action=`` one of
    canary / promote / abort / rollback / pin / unpin / status, plus
    ``version=``, ``fraction=`` and ``model=`` as the action needs."""
    events = []
    for item in spec.split(";"):
        item = item.strip()
        if not item:
            continue
        ev = {}
        for pair in item.split(","):
            k, _, v = pair.partition("=")
            ev[k.strip()] = v.strip()
        if ev.get("action") not in ("canary", "promote", "abort",
                                    "rollback", "pin", "unpin",
                                    "status"):
            raise SystemExit("rollout event %r needs action=canary|"
                             "promote|abort|rollback|pin|unpin|status"
                             % item)
        if "after" not in ev and "at_step" not in ev:
            raise SystemExit("rollout event %r needs after= or "
                             "at_step=" % item)
        events.append(ev)
    return events


def _wait_port(host, port, timeout=60.0):
    """Block until something accepts on host:port (a just-spawned
    server is still importing for a few seconds)."""
    import socket
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with socket.create_connection((host, port), timeout=1.0):
                return True
        except OSError:
            time.sleep(0.1)
    return False


def launch_local(args, command):
    procs = []
    base_env = dict(os.environ)
    coordinator = "127.0.0.1:%d" % args.port
    if args.autoscale:
        # the closed loop needs its sensor plane: the controller's only
        # input is the aggregator's fleet.json
        args.telemetry = True
    # -s N starts N async parameter-server processes (DMLC_ROLE=server;
    # reference dmlc-tracker starts ps-lite servers the same way); workers
    # find them via MXTPU_PS_ADDRS for create('dist_async')
    server_procs = []
    server_ports = []
    ps_addrs = []
    # per-launch shared secret: the PS wire protocol is pickle, so only
    # processes of THIS launch may speak to the servers (any other local
    # user connecting would otherwise get arbitrary code execution)
    ps_token = secrets.token_hex(16) if args.num_servers else None
    if ps_token:
        base_env["MXTPU_PS_TOKEN"] = ps_token
    # --telemetry: one observability plane for the whole launch
    # (docs/observability.md). Every child inherits MXTPU_TELEMETRY /
    # MXTPU_TELEMETRY_DIR — workers start metrics exporters and drop
    # endpoint files, servers/replicas answer `metrics` on their main
    # ports — and ONE aggregator child polls the fleet into
    # <dir>/fleet.json (+ history), which tools/mxtop.py renders live.
    if args.telemetry:
        if not args.telemetry_dir:
            args.telemetry_dir = tempfile.mkdtemp(prefix="mxtpu_telem_")
        base_env["MXTPU_TELEMETRY"] = "1"
        base_env["MXTPU_TELEMETRY_DIR"] = args.telemetry_dir
        print("telemetry: %s/fleet.json (mxtop: python tools/mxtop.py "
              "--dir %s)" % (args.telemetry_dir, args.telemetry_dir),
              flush=True)
    # -- autoscale plumbing (docs/autoscaling.md): the action mailbox /
    # journal / lease directory, shared by the controller child and this
    # launcher's executor; plus the prewarm dir serving replicas export
    # their AOT program menus into so a controller-added replica boots
    # warm. Provisioned before any child spawns so every env inherits it.
    autoscale_dir = None
    if args.autoscale or args.scale:
        autoscale_dir = args.autoscale_dir or (
            os.path.join(args.telemetry_dir, "autoscale")
            if args.telemetry_dir
            else tempfile.mkdtemp(prefix="mxtpu_autoscale_"))
        os.makedirs(autoscale_dir, exist_ok=True)
        base_env["MXTPU_AUTOSCALE_DIR"] = autoscale_dir
    if args.autoscale and args.serve:
        prewarm_dir = os.path.join(autoscale_dir, "prewarm")
        os.makedirs(prewarm_dir, exist_ok=True)
        base_env.setdefault("MXTPU_SERVE_PREWARM_DIR", prewarm_dir)
        # persistent XLA compile cache for every child: a joiner's
        # jit compiles become cache loads too, not just its AOT menu
        base_env.setdefault("JAX_COMPILATION_CACHE_DIR",
                            os.path.join(autoscale_dir, "jaxcache"))
    if args.ps_respawn and not args.ps_snapshot_dir:
        # a respawned server with no snapshot restores nothing and every
        # in-flight key 404s — auto-provision the state dir instead
        args.ps_snapshot_dir = tempfile.mkdtemp(prefix="mxtpu_ps_snap_")
        print("ps snapshots in %s" % args.ps_snapshot_dir)
    replicas = max(1, args.ps_replicas)
    # slot metadata drives both the first spawn and every respawn:
    # (name, port, role, peer address). With --ps-replicas 2 the slots
    # are N primaries followed by their N backups, wired pairwise.
    server_slots = []
    backup_addrs = []
    ports = [_free_port(args.port + 1 + s)
             for s in range(args.num_servers * (2 if replicas >= 2
                                                else 1))]
    for s in range(args.num_servers):
        ps_addrs.append("127.0.0.1:%d" % ports[s])
    if replicas >= 2:
        for s in range(args.num_servers):
            backup_addrs.append(
                "127.0.0.1:%d" % ports[args.num_servers + s])
        base_env["MXTPU_PS_REPLICAS"] = str(replicas)
        base_env["MXTPU_PS_REPL_MODE"] = args.ps_repl_mode
    for s in range(args.num_servers):
        peer = backup_addrs[s] if replicas >= 2 else None
        server_slots.append(("%d" % s, ports[s], "primary", peer))
    for s in range(args.num_servers) if replicas >= 2 else []:
        server_slots.append(("%d_backup" % s,
                             ports[args.num_servers + s], "backup",
                             ps_addrs[s]))
    for name, port, role, peer in server_slots:
        server_ports.append(port)
        server_procs.append(_spawn_server(name, port, base_env, args,
                                          role=role, peer=peer))
    if backup_addrs:
        base_env["MXTPU_PS_BACKUP_ADDRS"] = ",".join(backup_addrs)
    # --serve N: a model-serving replica set next to (or instead of)
    # the parameter servers; workers see MXTPU_SERVE_ADDRS and speak
    # mxtpu.serving.ServingClient (docs/serving.md)
    serve_addrs = []
    serve_live = []
    serve_reserve = []   # (idx, port) slots held back for the
    #                      controller's add_replica actuation
    if args.serve:
        if not (args.serve_model and args.serve_data_shapes):
            raise SystemExit("--serve needs --serve-model and "
                             "--serve-data-shapes")
        # --serve-max reserves extra ports up front so the FULL
        # potential replica set is in MXTPU_SERVE_ADDRS from the first
        # hello: clients already know where a scaled-up replica will
        # appear, and failover finds it without a re-hello
        n_slots = max(args.serve, args.serve_max or 0)
        serve_ports = [_free_port(args.port + 201 + i)
                       for i in range(n_slots)]
        serve_addrs = ["127.0.0.1:%d" % p for p in serve_ports]
        serve_live = serve_addrs[:args.serve]
        serve_reserve = [(i, serve_ports[i])
                         for i in range(args.serve, n_slots)]
        base_env["MXTPU_SERVE_ADDRS"] = ",".join(serve_addrs)
        # the serve contract rides to the WORKERS too: a trainer
        # process publishing weights (WeightPublisher into the weight
        # dir, or kv.publish_version) needs the served model prefix
        # and the versioned snapshot dir the replicas follow
        base_env["MXTPU_SERVE_MODEL"] = args.serve_model
        base_env["MXTPU_SERVE_EPOCH"] = str(args.serve_epoch)
        base_env["MXTPU_SERVE_DATA_SHAPES"] = args.serve_data_shapes
        if args.serve_weight_dir:
            base_env["MXTPU_SERVE_WEIGHT_DIR"] = args.serve_weight_dir
        for i, port in enumerate(serve_ports[:args.serve]):
            server_slots.append(("serve%d" % i, port, "serving", i))
            server_ports.append(port)
            server_procs.append(_spawn_serving_replica(
                i, port, serve_addrs, base_env, args))
    # the aggregator child: polls every PS shard / backup / serving
    # replica (workers join via their endpoint files) into fleet.json.
    # Spawned AFTER the target lists exist, reaped with the servers.
    if args.telemetry:
        agg_env = dict(base_env, JAX_PLATFORMS="cpu")
        agg_env.pop("DMLC_ROLE", None)
        targets = ps_addrs + backup_addrs + serve_live
        agg = subprocess.Popen(
            [sys.executable, "-m", "mxtpu.obs.telemetry",
             "--targets", ",".join(targets),
             "--dir", args.telemetry_dir], env=agg_env)
        server_slots.append(("telemetry", 0, "telemetry", None))
        server_ports.append(0)
        server_procs.append(agg)
        print("telemetry aggregator pid=%d targets=%d"
              % (agg.pid, len(targets)), flush=True)

    # -- the autoscale controller child: the policy brain. It only ever
    # READS fleet.json and WRITES action files into the mailbox; this
    # launcher's executor (below) is the sole actuator. Separate process
    # so kill -9 mid-action is a first-class drill: the respawn replays
    # its journal and the executor dedupes (docs/autoscaling.md).
    def _spawn_controller(respawn=False):
        env = dict(base_env, JAX_PLATFORMS="cpu")
        env.pop("DMLC_ROLE", None)
        env["MXTPU_OBS_ROLE"] = "controller"
        if args.autoscale_fault and not respawn:
            env["MXTPU_FAULT_SPEC"] = args.autoscale_fault
        elif respawn:
            # a controller fault drill is one-shot: the respawned
            # controller must replay its journal, not re-die on the
            # same injected kill
            env.pop("MXTPU_FAULT_SPEC", None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "mxtpu.fleet.controller",
             "--dir", autoscale_dir,
             "--fleet", os.path.join(args.telemetry_dir, "fleet.json")],
            env=env)
        print("autoscale controller pid=%d dir=%s"
              % (proc.pid, autoscale_dir), flush=True)
        return proc

    if args.autoscale:
        server_slots.append(("controller", 0, "controller", None))
        server_ports.append(0)
        server_procs.append(_spawn_controller())
    if args.worker_respawn and not args.worker_state_dir:
        # a respawned worker with no state dir restarts from step 0 and
        # double-trains its epoch — auto-provision one, like --ps-respawn
        args.worker_state_dir = tempfile.mkdtemp(prefix="mxtpu_worker_")
        print("worker state in %s" % args.worker_state_dir)
    worker_envs = []
    for rank in range(args.num_workers):
        env = dict(base_env)
        env.update({
            "MXTPU_COORDINATOR": coordinator,
            "MXTPU_NUM_PROCS": str(args.num_workers),
            "MXTPU_PROC_ID": str(rank),
            # reference-compatible aliases
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_NUM_SERVER": str(args.num_servers),
            "DMLC_WORKER_ID": str(rank),
        })
        if ps_addrs:
            env["MXTPU_PS_ADDRS"] = ",".join(ps_addrs)
        if args.worker_state_dir:
            # per-rank checkpoint dir a TrainGuard/CheckpointManager
            # worker saves its state into; the respawn reuses it so the
            # fresh process restores and fast-forwards
            env["MXTPU_WORKER_STATE_DIR"] = os.path.join(
                args.worker_state_dir, "worker_%d" % rank)
        worker_envs.append(env)
        procs.append(subprocess.Popen(command, shell=True, env=env))
    code = 0
    respawns = [0] * len(server_procs)
    worker_respawns = [0] * len(procs)

    # -- the --scale drill: elastic add/remove/split events on a
    # wall-clock or training-progress schedule (docs/fault_tolerance.md
    # "Elasticity"). Runs on its own thread; the monitor loop below
    # waits for it before declaring the launch finished.
    scale_done = threading.Event()
    stop_scale = threading.Event()
    removed = set()    # ranks departed by a remove_worker event: their
    #                    sh -c wrapper dies -15, which is NOT a failure
    drained_slots = set()   # server_slots indices drained on purpose
    actuate_lock = threading.Lock()   # one actuation mutates the fleet
    #                                   at a time (executor thread +
    #                                   --scale thread both actuate)

    def _announce_endpoint(role, addr):
        """Dynamically added children (replicas, split shards) are not
        in the aggregator's static target list — an endpoint file is
        how they join the telemetry plane mid-run."""
        if not args.telemetry_dir:
            return
        epd = os.path.join(args.telemetry_dir, "endpoints")
        os.makedirs(epd, exist_ok=True)
        path = os.path.join(epd,
                            "%s-%s.ep" % (role, addr.replace(":", "-")))
        tmp = path + ".tmp%d" % os.getpid()
        with open(tmp, "w") as f:
            f.write(addr)
        os.replace(tmp, path)

    def _retract_endpoint(role, addr):
        if not args.telemetry_dir:
            return
        try:
            os.unlink(os.path.join(
                args.telemetry_dir, "endpoints",
                "%s-%s.ep" % (role, addr.replace(":", "-"))))
        except OSError:
            pass

    def _act_add_worker(action=None):
        rank = len(procs)
        env = dict(base_env)
        env.update({
            "MXTPU_NUM_PROCS": str(args.num_workers),
            "MXTPU_PROC_ID": str(rank),
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_NUM_SERVER": str(args.num_servers),
            "DMLC_WORKER_ID": str(rank),
            # the joiner contract: skip init/set_optimizer, pull
            # current params, take work from the shard cursor
            "MXTPU_ELASTIC_JOINER": "1",
        })
        # a mid-run joiner CANNOT enter the already-formed
        # jax.distributed group (the coordination service pins its
        # world size at bootstrap) — elasticity rides the PS layer,
        # so the joiner runs single-process XLA and shares the
        # model only through the parameter servers
        env.pop("MXTPU_COORDINATOR", None)
        if ps_addrs:
            env["MXTPU_PS_ADDRS"] = ",".join(ps_addrs)
        if args.worker_state_dir:
            env["MXTPU_WORKER_STATE_DIR"] = os.path.join(
                args.worker_state_dir, "worker_%d" % rank)
        print("scale: adding worker %d" % rank, flush=True)
        worker_envs.append(env)
        worker_respawns.append(0)
        procs.append(subprocess.Popen(command, shell=True, env=env))
        return {"rank": rank}

    def _worker_rank_for_pid(pid):
        """Rank whose process tree contains pid — telemetry snapshots
        carry the python worker's pid, but the tracked Popen is its
        sh -c wrapper."""
        for rank, wp in enumerate(procs):
            if rank in removed or wp.poll() is not None:
                continue
            if wp.pid == pid:
                return rank
            try:
                for task in os.listdir("/proc/%d/task" % wp.pid):
                    with open("/proc/%d/task/%s/children"
                              % (wp.pid, task)) as f:
                        if pid in [int(c) for c in f.read().split()]:
                            return rank
            except OSError:
                continue
        return None

    def _act_remove_worker(action=None):
        action = action or {}
        rank = None
        if action.get("rank") is not None:
            rank = int(action["rank"])
        elif action.get("pid") is not None:
            rank = _worker_rank_for_pid(int(action["pid"]))
        if rank is None:
            live = [r for r, wp in enumerate(procs)
                    if r not in removed and wp.poll() is None]
            if not live:
                raise RuntimeError("no live worker to remove")
            rank = live[-1]
        # SIGTERM is the CLEAN departure: an elastic worker's
        # handler finishes its current shard, byes, and exits 0.
        # Popen(shell=True) makes the tracked pid an sh -c wrapper,
        # so the signal must reach its CHILDREN (the python worker)
        # too, or only the shell dies and training runs on.
        print("scale: removing worker %d (SIGTERM)" % rank,
              flush=True)
        removed.add(rank)
        pid = procs[rank].pid
        kids = []
        try:
            for task in os.listdir("/proc/%d/task" % pid):
                with open("/proc/%d/task/%s/children"
                          % (pid, task)) as f:
                    kids += [int(c) for c in f.read().split()]
        except OSError:
            pass
        for target in kids + [pid]:
            try:
                os.kill(target, signal.SIGTERM)
            except OSError:
                pass
        return {"rank": rank}

    def _act_split_shard(action=None):
        action = action or {}
        idx = len(server_slots)
        port = _free_port(args.port + 101 + idx)
        dst_addr = "127.0.0.1:%d" % port
        slots = [("e%d" % idx, port, "primary", None)]
        if max(1, args.ps_replicas) >= 2:
            # the new shard is born replicated: its backup joins
            # and catches up, and every adopted key mirrors there
            # BEFORE the old primary releases it
            bport = _free_port(args.port + 151 + idx)
            slots = [("e%d" % idx, port, "primary",
                      "127.0.0.1:%d" % bport),
                     ("e%d_backup" % idx, bport, "backup",
                      dst_addr)]
        for name, p_, role, peer in slots:
            server_slots.append((name, p_, role, peer))
            respawns.append(0)
            server_ports.append(p_)
            server_procs.append(_spawn_server(
                name, p_, base_env, args, role=role, peer=peer))
        if not _wait_port("127.0.0.1", port):
            raise RuntimeError(
                "split target %s never came up" % dst_addr)
        src_addr = action.get("src_addr") \
            or ps_addrs[int(action.get("src", 0))]
        admin_env = dict(base_env)
        admin_env.pop("DMLC_ROLE", None)
        admin_env["JAX_PLATFORMS"] = "cpu"
        print("scale: splitting server %s -> %s"
              % (src_addr, dst_addr), flush=True)
        r = subprocess.run(
            [sys.executable, "-m", "mxtpu.kvstore_async",
             "--admin", "split", "--src", src_addr,
             "--dst", dst_addr],
            env=admin_env, capture_output=True, text=True)
        print("scale: split -> %s"
              % (r.stdout.strip() or r.stderr.strip()[-500:]),
              flush=True)
        if r.returncode != 0:
            raise RuntimeError("split admin failed: %s"
                               % r.stderr.strip()[-300:])
        for name, p_, role, peer in slots:
            _announce_endpoint("server", "127.0.0.1:%d" % p_)
        return {"src": src_addr, "dst": dst_addr}

    def _act_add_replica(action=None):
        if not serve_reserve:
            raise RuntimeError(
                "no reserved serving slot left (--serve-max)")
        i, port = serve_reserve.pop(0)
        addr = "127.0.0.1:%d" % port
        print("scale: adding serving replica %d on %s" % (i, addr),
              flush=True)
        server_slots.append(("serve%d" % i, port, "serving", i))
        respawns.append(0)
        server_ports.append(port)
        server_procs.append(_spawn_serving_replica(
            i, port, serve_addrs, base_env, args))
        if not _wait_port("127.0.0.1", port, timeout=180):
            raise RuntimeError("replica %s never came up" % addr)
        _announce_endpoint("serving", addr)
        return {"addr": addr}

    def _act_drain_replica(action=None):
        action = action or {}
        target = None
        for si, (name, port, role, peer) in enumerate(server_slots):
            if role != "serving" or si in drained_slots:
                continue
            sp = server_procs[si]
            if sp.poll() is not None:
                continue
            addr = "127.0.0.1:%d" % port
            if action.get("addr") in (None, addr):
                target = (si, addr, sp)
                if action.get("addr"):
                    break
        if target is None:
            raise RuntimeError("no live serving replica to drain (%r)"
                               % action.get("addr"))
        si, addr, sp = target
        print("scale: draining serving replica %s (SIGTERM)" % addr,
              flush=True)
        drained_slots.add(si)    # respawn loop must not revive it
        sp.send_signal(signal.SIGTERM)   # graceful drain, exits 0
        _retract_endpoint("serving", addr)
        return {"addr": addr}

    # -- the idempotent actuation layer: EVERY fleet mutation (the
    # --scale drill's scripted events AND the --autoscale controller's
    # mailbox actions) goes through ONE ActionExecutor keyed by action
    # id, so a re-issued action after an ambiguous timeout returns the
    # recorded verdict instead of double-applying.
    executor = None
    if args.scale or args.autoscale:
        from mxtpu.fleet.actuator import ActionExecutor
        handlers = {}
        for kind, fn in (("add_worker", _act_add_worker),
                         ("remove_worker", _act_remove_worker),
                         ("split_shard", _act_split_shard),
                         ("add_replica", _act_add_replica),
                         ("drain_replica", _act_drain_replica)):
            def _locked(action=None, _fn=fn):
                with actuate_lock:
                    return _fn(action)
            handlers[kind] = _locked
        executor = ActionExecutor(autoscale_dir, handlers)

    def _do_scale_event(ev, idx):
        # position-derived id: a re-issued event after an ambiguous
        # timeout hits the executor's verdict record, not the handler
        eid = "scale-%d-%s" % (idx, ev["action"])
        v = executor.execute(eid, dict(ev)) or {}
        print("scale: %s -> %s %s"
              % (eid, v.get("verdict"), str(v.get("detail"))[:200]),
              flush=True)

    def _scale_controller(events):
        t0 = time.time()
        try:
            for idx, ev in enumerate(events):
                if "after" in ev:
                    deadline = t0 + float(ev["after"])
                    while time.time() < deadline:
                        if stop_scale.is_set():
                            return
                        time.sleep(0.05)
                else:
                    want = int(ev["at_step"])
                    while True:
                        if stop_scale.is_set():
                            return
                        try:
                            with open(args.scale_progress) as f:
                                step = int(f.read() or 0)
                        except (OSError, ValueError):
                            step = 0
                        if step >= want:
                            break
                        time.sleep(0.05)
                try:
                    _do_scale_event(ev, idx)
                except Exception as e:   # a drill bug must not wedge
                    print("scale: event %r failed: %s" % (ev, e),
                          flush=True)
        finally:
            scale_done.set()

    if args.scale:
        events = _parse_scale(args.scale)
        if any("at_step" in e for e in events) \
                and not args.scale_progress:
            raise SystemExit("--scale with at_step= triggers needs "
                             "--scale-progress FILE")
        threading.Thread(target=_scale_controller, args=(events,),
                         daemon=True).start()
    else:
        scale_done.set()

    # -- the mailbox pump: applies controller-submitted actions through
    # the executor (each at most once) and writes their verdict files
    stop_exec = threading.Event()
    if args.autoscale:
        def _exec_loop():
            while not stop_exec.wait(0.2):
                try:
                    executor.poll()
                except Exception as e:   # an actuator bug must not
                    #                      kill the pump
                    print("autoscale: executor error: %s" % e,
                          flush=True)
        threading.Thread(target=_exec_loop, daemon=True).start()

    # -- the --rollout drill: canary/promote/abort/rollback events on a
    # wall-clock or progress schedule, driven through the serving admin
    # wire (python -m mxtpu.serving --admin rollout). The scriptable
    # form of the continuous-deployment story: a canary split under
    # real traffic, a verdict, a bit-exact rollback — all while the
    # fleet keeps answering (docs/serving.md "Rollout & weight
    # streaming").
    rollout_done = threading.Event()

    def _do_rollout_event(ev):
        cmd = [sys.executable, "-m", "mxtpu.serving",
               "--admin", "rollout", "--addrs", ",".join(serve_addrs),
               "--action", ev["action"]]
        if ev.get("version"):
            cmd += ["--version", ev["version"]]
        if ev.get("fraction"):
            cmd += ["--fraction", ev["fraction"]]
        if ev.get("model"):
            cmd += ["--model", ev["model"]]
        admin_env = dict(base_env)
        admin_env.pop("DMLC_ROLE", None)
        admin_env["JAX_PLATFORMS"] = "cpu"
        print("rollout: %s" % " ".join(cmd[3:]), flush=True)
        r = subprocess.run(cmd, env=admin_env, capture_output=True,
                           text=True)
        print("rollout: %s -> %s"
              % (ev["action"],
                 (r.stdout.strip() or r.stderr.strip())[-500:]),
              flush=True)

    def _rollout_controller(events):
        t0 = time.time()
        try:
            for ev in events:
                if "after" in ev:
                    deadline = t0 + float(ev["after"])
                    while time.time() < deadline:
                        if stop_scale.is_set():
                            return
                        time.sleep(0.05)
                else:
                    want = int(ev["at_step"])
                    while True:
                        if stop_scale.is_set():
                            return
                        try:
                            with open(args.scale_progress) as f:
                                step = int(f.read() or 0)
                        except (OSError, ValueError):
                            step = 0
                        if step >= want:
                            break
                        time.sleep(0.05)
                try:
                    _do_rollout_event(ev)
                except Exception as e:   # a drill bug must not wedge
                    print("rollout: event %r failed: %s" % (ev, e),
                          flush=True)
        finally:
            rollout_done.set()

    if args.rollout:
        if not serve_addrs:
            raise SystemExit("--rollout needs --serve N")
        events = _parse_rollout(args.rollout)
        if any("at_step" in e for e in events) \
                and not args.scale_progress:
            raise SystemExit("--rollout with at_step= triggers needs "
                             "--scale-progress FILE")
        threading.Thread(target=_rollout_controller, args=(events,),
                         daemon=True).start()
    else:
        rollout_done.set()
    try:
        # respawn passes run BEFORE the liveness check: a fleet whose
        # last worker just got kill -9'd must be revived, not reaped
        # (with -n 1 the old any-alive loop condition would exit first)
        while True:
            if args.worker_respawn:
                for i, wp in enumerate(list(procs)):
                    rc = wp.poll()
                    if rc is None or rc == 0 or i in removed:
                        continue   # alive, finished cleanly, or departed
                    if worker_respawns[i] >= args.worker_max_respawns:
                        continue   # budget spent: the exit code stands
                    worker_respawns[i] += 1
                    print("worker %d died (exit %d); respawning "
                          "(%d/%d)" % (i, rc, worker_respawns[i],
                                       args.worker_max_respawns),
                          flush=True)
                    procs[i] = subprocess.Popen(
                        command, shell=True, env=worker_envs[i])
            if args.ps_respawn or args.serve_respawn or args.autoscale:
                for i, sp in enumerate(server_procs):
                    rc = sp.poll()
                    if rc is None or rc == 0:
                        continue   # alive, or clean 'stop' exit
                    name, port, role, peer = server_slots[i]
                    if role == "telemetry":
                        continue   # observability is passive: a dead
                        #            aggregator is a gap, not a respawn
                    if role == "controller":
                        # --autoscale implies the controller must live:
                        # the revived process re-takes the lease (epoch
                        # bump fences any straggler) and replays its
                        # journal — kill -9 mid-action is the drill
                        if not args.autoscale or respawns[i] >= 5:
                            continue
                        respawns[i] += 1
                        print("autoscale controller died (exit %s); "
                              "respawning (%d/5)" % (rc, respawns[i]),
                              flush=True)
                        server_procs[i] = _spawn_controller(
                            respawn=True)
                        continue
                    if i in drained_slots:
                        continue   # departed on purpose, stays down
                    if role != "serving" and (
                            not args.ps_respawn
                            or respawns[i] >= args.ps_max_respawns):
                        continue   # workers' retry layer surfaces it
                    if role == "serving":
                        # without --serve-respawn a crashed serving
                        # replica is the failover drill's subject:
                        # clients re-route to the survivors. WITH it,
                        # the rejoin is well-defined now that weights
                        # are versioned: the revived process re-binds
                        # its port, catches up to the current weight
                        # version BEFORE admitting, and re-hellos.
                        if not args.serve_respawn or \
                                respawns[i] >= args.serve_max_respawns:
                            continue
                        respawns[i] += 1
                        print("serve replica %s died (exit %d); "
                              "respawning on port %d (%d/%d)"
                              % (name, rc, port, respawns[i],
                                 args.serve_max_respawns), flush=True)
                        server_procs[i] = _spawn_serving_replica(
                            peer, port, serve_addrs, base_env, args)
                        continue
                    respawns[i] += 1
                    print("server %s died (exit %d); respawning on port "
                          "%d (%d/%d)" % (name, rc, port, respawns[i],
                                          args.ps_max_respawns),
                          flush=True)
                    # env role is only the opening bid: the respawned
                    # process probes its peer at boot and, if the peer
                    # was promoted meanwhile, rejoins as the new backup
                    server_procs[i] = _spawn_server(
                        name, port, base_env, args, role=role,
                        peer=peer)
            if all(p.poll() is not None for p in procs):
                if not scale_done.is_set() or not rollout_done.is_set():
                    # workers drained before a drill finished: stop
                    # the controllers (bounded) rather than hanging on
                    # a progress file nobody writes anymore
                    stop_scale.set()
                    scale_done.wait(timeout=10)
                    rollout_done.wait(timeout=10)
                if all(p.poll() is not None for p in procs):
                    break
            time.sleep(0.2)
        for i, p in enumerate(procs):
            if i in removed:
                continue   # a drill departure is a clean exit
            code = code or p.returncode
    except KeyboardInterrupt:
        _reap(procs)
        code = 1
    finally:
        stop_exec.set()
        # servers ignore nothing a worker still needs by now: reap with
        # TERM->KILL escalation so a hung server cannot zombie-leak or
        # wedge the launcher's exit
        _reap(server_procs)
    return code


def launch_ssh(args, command):
    hosts = [h.strip() for h in open(args.hostfile) if h.strip()]
    coordinator = "%s:%d" % (hosts[0], args.port)
    procs = []
    for rank in range(args.num_workers):
        host = hosts[rank % len(hosts)]
        envs = ("MXTPU_COORDINATOR=%s MXTPU_NUM_PROCS=%d MXTPU_PROC_ID=%d "
                "DMLC_ROLE=worker DMLC_NUM_WORKER=%d DMLC_NUM_SERVER=%d "
                "DMLC_WORKER_ID=%d"
                % (coordinator, args.num_workers, rank, args.num_workers,
                   args.num_servers, rank))
        remote = "ssh -o StrictHostKeyChecking=no %s 'cd %s && %s %s'" % (
            host, os.getcwd(), envs, command)
        print(remote)
        if not args.dry_run:
            procs.append(subprocess.Popen(remote, shell=True))
    code = 0
    try:
        for p in procs:
            # remote jobs run arbitrarily long; ^C is the operator's
            # abort and is handled below with a bounded reap
            p.wait()   # mxlint: allow(blocking-call) — foreground wait on remote jobs; ^C aborts
            code = code or p.returncode
    except KeyboardInterrupt:
        _reap(procs)
        code = 1
    return code


def _env_exports(args, coordinator_host, rank_expr, sep="; "):
    """The single source of the MXTPU_*/DMLC_* worker env contract; each
    cluster launcher supplies only its scheduler's rank expression."""
    return sep.join([
        "export MXTPU_COORDINATOR=%s:%d MXTPU_NUM_PROCS=%d"
        % (coordinator_host, args.port, args.num_workers),
        "export MXTPU_PROC_ID=%s" % rank_expr,
        "export DMLC_ROLE=worker DMLC_NUM_WORKER=%d DMLC_NUM_SERVER=%d "
        "DMLC_WORKER_ID=$MXTPU_PROC_ID" % (args.num_workers,
                                           args.num_servers),
    ])


def _coordinator_host(args, scheduler):
    """Rank 0's host. mpi derives it from the hostfile when given; the
    scheduler modes (slurm/sge) allocate nodes at submit time, so a
    reachable --coordinator-host must be provided for multi-node jobs."""
    if scheduler == "mpi" and args.hostfile:
        with open(args.hostfile) as f:
            for line in f:
                host = line.split()[0] if line.strip() else ""
                if host:
                    return host
    return args.coordinator_host


def launch_mpi(args, command):
    """mpirun dispatch (reference dmlc-tracker/mpi.py): one rank per
    worker; each rank derives its identity from OMPI/PMI env vars via the
    wrapper below, so the same worker script runs under every launcher."""
    wrapper = "%s; %s" % (
        _env_exports(args, _coordinator_host(args, "mpi"),
                     "${OMPI_COMM_WORLD_RANK:-${PMI_RANK:-0}}"), command)
    cmd = ["mpirun", "-np", str(args.num_workers)]
    if args.hostfile:
        cmd += ["--hostfile", args.hostfile]
    cmd += ["bash", "-c", wrapper]
    print(" ".join("'%s'" % c if " " in c else c for c in cmd))
    if args.dry_run:
        return 0
    return subprocess.call(cmd)


def launch_slurm(args, command):
    """srun dispatch (the modern cluster-scheduler analogue of the
    reference's sge/yarn trackers): SLURM_PROCID provides the rank.
    Multi-node jobs must pass --coordinator-host (a node reachable by all
    ranks) since nodes are allocated by the scheduler at submit time."""
    wrapper = "%s; %s" % (
        _env_exports(args, _coordinator_host(args, "slurm"),
                     "$SLURM_PROCID"), command)
    cmd = ["srun", "--ntasks=%d" % args.num_workers, "bash", "-c", wrapper]
    print(" ".join("'%s'" % c if " " in c else c for c in cmd))
    if args.dry_run:
        return 0
    return subprocess.call(cmd)


def launch_sge(args, command):
    """SGE array-job dispatch (reference dmlc-tracker/sge.py): submits a
    task-array of size N; SGE_TASK_ID (1-based) provides the rank.
    Multi-node jobs must pass --coordinator-host (see launch_slurm)."""
    script = "#!/bin/bash\n#$ -t 1-%d\n#$ -cwd\n#$ -S /bin/bash\n%s\n%s\n" % (
        args.num_workers,
        _env_exports(args, _coordinator_host(args, "sge"),
                     "$((SGE_TASK_ID - 1))", sep="\n"),
        command)
    print(script)
    if args.dry_run:
        return 0
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".sh",
                                     delete=False) as f:
        f.write(script)
        path = f.name
    return subprocess.call(["qsub", "-sync", "y", path])


# Kubernetes / GKE (the modern yarn analogue): no dispatch code needed —
# run the worker as an indexed Job / JobSet with
#   MXTPU_COORDINATOR=<job>-0.<headless-svc>:9327
#   MXTPU_NUM_PROCS=<parallelism>
#   MXTPU_PROC_ID=$JOB_COMPLETION_INDEX
# which is exactly the env contract every launcher above emits. On Cloud
# TPU pods, jax.distributed.initialize() with no args uses the TPU
# metadata server instead and none of this is required.


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("-s", "--num-servers", type=int, default=0,
                   help="async parameter-server processes for "
                        "create('dist_async'); sync mode needs none "
                        "(SPMD collectives instead)")
    p.add_argument("--ps-replicas", type=int,
                   default=int(os.environ.get("MXTPU_PS_REPLICAS",
                                              "1")),
                   help="2 pairs every parameter-server shard with a "
                        "hot backup: applied updates replicate over "
                        "the primary's stream, clients fail over in "
                        "place on a primary death, and a respawned "
                        "server rejoins as the new backup "
                        "(docs/fault_tolerance.md, 'Replication & "
                        "failover')")
    p.add_argument("--ps-repl-mode", choices=("sync", "async"),
                   default=os.environ.get("MXTPU_PS_REPL_MODE",
                                          "sync"),
                   help="sync (default): a push is acked only after "
                        "the backup acked the forwarded update — zero "
                        "acknowledged-update loss on a primary kill; "
                        "async: ack immediately, replication lag "
                        "bounded by MXTPU_PS_REPL_LAG_MAX")
    p.add_argument("--ps-respawn", action="store_true",
                   help="local launcher: respawn a crashed parameter "
                        "server on its original port; with snapshots it "
                        "restores its table and workers reconverge")
    p.add_argument("--ps-max-respawns", type=int, default=3,
                   help="respawn budget per server before its death is "
                        "left to the workers' retry layer")
    p.add_argument("--ps-snapshot-dir", default=None,
                   help="base dir for per-server state snapshots "
                        "(server i uses <dir>/server_i); auto-created "
                        "under $TMPDIR when --ps-respawn is on")
    p.add_argument("--ps-snapshot-every", type=int, default=100,
                   help="pushes between server snapshots")
    p.add_argument("--worker-respawn", action="store_true",
                   help="local launcher: respawn a worker that exits "
                        "non-zero (kill -9 included); with a state dir "
                        "the fresh process restores its checkpoint, "
                        "re-registers with the servers and fast-forwards "
                        "its data iterator (mxtpu.resilience.TrainGuard)")
    p.add_argument("--worker-max-respawns", type=int, default=3,
                   help="respawn budget per worker before its death "
                        "is final")
    p.add_argument("--worker-state-dir", default=None,
                   help="base dir for per-worker checkpoints (rank r "
                        "uses <dir>/worker_r, exported as "
                        "MXTPU_WORKER_STATE_DIR); auto-created under "
                        "$TMPDIR when --worker-respawn is on")
    p.add_argument("--scale", default=None,
                   help="local launcher elasticity drill: ';'-separated "
                        "events of 'after=SECS|at_step=N,action="
                        "add_worker|remove_worker|split_shard"
                        "[,rank=R][,src=I]' — add_worker spawns a "
                        "joining worker (MXTPU_ELASTIC_JOINER=1), "
                        "remove_worker SIGTERMs one (clean departure), "
                        "split_shard spawns a fresh server (pair, with "
                        "--ps-replicas 2) and splits server slot I's "
                        "keys onto it online (docs/fault_tolerance.md "
                        "'Elasticity')")
    p.add_argument("--autoscale", action="store_true",
                   help="local launcher: close the loop — spawn the "
                        "autoscaling controller child (python -m "
                        "mxtpu.fleet.controller), which reads the "
                        "telemetry plane's fleet.json and submits "
                        "add/remove-worker, split-shard and add/drain-"
                        "replica actions into the action mailbox; THIS "
                        "launcher executes them idempotently and "
                        "respawns a crashed controller (journal "
                        "replay). Implies --telemetry. Policy knobs "
                        "ride MXTPU_AUTOSCALE_* env vars "
                        "(docs/autoscaling.md)")
    p.add_argument("--autoscale-dir", default=None,
                   help="action mailbox / journal / lease dir (default "
                        "<telemetry-dir>/autoscale); exported as "
                        "MXTPU_AUTOSCALE_DIR")
    p.add_argument("--autoscale-fault", default=None,
                   help="MXTPU_FAULT_SPEC for the controller child "
                        "ONLY (e.g. 'point=ctl.action,kind=kill_worker"
                        ",nth=1' for the kill-mid-action drill); "
                        "dropped on respawn so the drill is one-shot")
    p.add_argument("--serve-max", type=int, default=0,
                   help="reserve serving ports up to this count so the "
                        "autoscale controller can add replicas beyond "
                        "--serve N; the FULL slot set is advertised in "
                        "MXTPU_SERVE_ADDRS from the start (default: "
                        "no headroom)")
    p.add_argument("--serve", type=int, default=0,
                   help="local launcher: start N model-serving replicas "
                        "(python -m mxtpu.serving) and export "
                        "MXTPU_SERVE_ADDRS to the workers; replicas "
                        "drain gracefully on SIGTERM (the _reap "
                        "escalation's TERM phase) and a kill -9'd "
                        "replica is the client-failover drill "
                        "(docs/serving.md)")
    p.add_argument("--serve-model", default=None,
                   help="checkpoint prefix the replicas load "
                        "(prefix-symbol.json + prefix-%%04d.params)")
    p.add_argument("--serve-epoch", type=int, default=0,
                   help="checkpoint epoch for --serve-model")
    p.add_argument("--serve-data-shapes", default=None,
                   help="per-sample input shapes for the served model, "
                        "'name=dims[;name=dims]' (e.g. data=3,32,32)")
    p.add_argument("--serve-buckets", default=None,
                   help="batch buckets the replicas AOT-compile "
                        "(default 1,2,4,8,16,32)")
    p.add_argument("--serve-respawn", action="store_true",
                   help="local launcher: respawn a kill -9'd serving "
                        "replica on its original port — the fresh "
                        "process catches up to the CURRENT weight "
                        "version before admitting, then re-hellos "
                        "(docs/serving.md 'Rollout & weight "
                        "streaming')")
    p.add_argument("--serve-max-respawns", type=int, default=3,
                   help="respawn budget per serving replica before "
                        "its death is left to client failover")
    p.add_argument("--serve-weight-dir", default=None,
                   help="versioned weight-snapshot dir the replicas "
                        "follow (WeightPublisher's output; exported "
                        "as MXTPU_SERVE_WEIGHT_DIR) — also the "
                        "rollback restore source")
    p.add_argument("--serve-weight-kv", default=None,
                   help="comma list of parameter-server addresses the "
                        "replicas follow via the 'weights' long-poll "
                        "stream (exported as MXTPU_SERVE_WEIGHT_KV)")
    p.add_argument("--serve-weight-poll", type=float, default=None,
                   help="weight-sync tick seconds (exported as "
                        "MXTPU_SERVE_WEIGHT_POLL; default 0.5)")
    p.add_argument("--rollout", default=None,
                   help="serving rollout drill: ';'-separated events "
                        "of 'after=SECS|at_step=N,action=canary|"
                        "promote|abort|rollback|pin|unpin|status"
                        "[,version=V][,fraction=F][,model=M]' driven "
                        "through the serving admin wire (python -m "
                        "mxtpu.serving --admin rollout); at_step= "
                        "reads --scale-progress")
    p.add_argument("--scale-progress", default=None,
                   help="progress file written by the training script; "
                        "at_step= scale triggers fire when its integer "
                        "content reaches N")
    p.add_argument("--telemetry", action="store_true",
                   help="local launcher: export MXTPU_TELEMETRY to "
                        "every child (workers start metrics "
                        "exporters) and spawn ONE aggregator that "
                        "polls the fleet's `metrics` ops into "
                        "<telemetry-dir>/fleet.json + history; render "
                        "it live with tools/mxtop.py "
                        "(docs/observability.md)")
    p.add_argument("--telemetry-dir", default=None,
                   help="telemetry rendezvous dir (endpoint files + "
                        "fleet.json); auto-created under $TMPDIR when "
                        "--telemetry is on")
    p.add_argument("--launcher",
                   choices=("local", "ssh", "mpi", "slurm", "sge"),
                   default="local")
    p.add_argument("-H", "--hostfile", default=None)
    p.add_argument("--port", type=int, default=9327)
    p.add_argument("--coordinator-host", default="127.0.0.1",
                   help="host of rank 0 for mpi/slurm/sge modes")
    p.add_argument("--dry-run", action="store_true")
    p.add_argument("command", nargs="+")
    args = p.parse_args()
    command = " ".join(args.command)
    if args.launcher == "local":
        sys.exit(launch_local(args, command))
    if args.launcher == "mpi":
        sys.exit(launch_mpi(args, command))
    if args.launcher == "slurm":
        sys.exit(launch_slurm(args, command))
    if args.launcher == "sge":
        sys.exit(launch_sge(args, command))
    if not args.hostfile:
        sys.exit("ssh launcher requires --hostfile")
    sys.exit(launch_ssh(args, command))


if __name__ == "__main__":
    main()
