#!/usr/bin/env python
"""mxlint entry point — AST-based static analysis for the mxtpu
concurrency, host-sync and donation contracts.

Thin launcher for the ``tools/mxlint/`` package so the canonical
invocation works from the repo root::

    python tools/mxlint.py mxtpu tools
    python tools/mxlint.py --diff          # only files changed vs main
    python tools/mxlint.py --list-passes

See ``docs/static_analysis.md`` for the pass catalog, pragma syntax and
baseline workflow; ``ci/check_static.py`` is the CI wrapper.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mxlint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
