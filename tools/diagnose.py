#!/usr/bin/env python
"""Environment diagnostic (reference tools/diagnose.py): OS / hardware /
python / mxtpu / backend sections, printable into bug reports.

The backend section probes the accelerator in a TIMEOUT-BOUNDED
subprocess (this environment's TPU relay can wedge indefinitely — an
in-process jax.devices() would hang the diagnostic itself; see
bench.py's probe).

Usage: python tools/diagnose.py [--timeout SECONDS]
"""
from __future__ import annotations

import argparse
import os
import platform

import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def section(title):
    print("-" * 24)
    print(title)
    print("-" * 24)


def check_os():
    section("Platform")
    print("system   :", platform.system(), platform.release())
    print("machine  :", platform.machine())
    print("version  :", platform.version())
    print("node     :", platform.node())


def check_hardware():
    section("Hardware")
    print("cpu_count:", os.cpu_count())
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith(("MemTotal", "MemAvailable")):
                    print(line.strip())
    except IOError:
        pass
    try:
        with open("/proc/cpuinfo") as f:
            models = [l.split(":", 1)[1].strip() for l in f
                      if l.startswith("model name")]
        if models:
            print("cpu model:", models[0], "x%d" % len(models))
    except IOError:
        pass


def check_python():
    section("Python")
    print("version  :", sys.version.replace("\n", " "))
    print("exe      :", sys.executable)
    for mod in ("numpy", "jax", "jaxlib", "flax", "optax", "orbax",
                "PIL", "cv2", "pandas", "torch"):
        try:
            m = __import__(mod)
            print("%-9s: %s" % (mod, getattr(m, "__version__", "present")))
        except ImportError:
            print("%-9s: NOT INSTALLED" % mod)


def check_mxtpu():
    section("mxtpu")
    try:
        import mxtpu
        print("version  :", getattr(mxtpu, "__version__", "dev"))
        print("path     :", os.path.dirname(mxtpu.__file__))
        from mxtpu.ops.registry import _REGISTRY
        canonical = {op.name for op in _REGISTRY.values()}
        print("ops      : %d canonical (%d incl. aliases)"
              % (len(canonical), len(_REGISTRY)))
        so = os.path.join(os.path.dirname(mxtpu.__file__), "_native")
        native = [f for f in os.listdir(so)
                  if f.endswith(".so")] if os.path.isdir(so) else []
        print("native   :", ", ".join(native) if native
              else "(not built; make -C mxtpu/_native)")
    except Exception as e:
        print("IMPORT FAILED:", repr(e))


def check_backend(timeout):
    section("Accelerator backend (bounded probe)")
    print("JAX_PLATFORMS =", os.environ.get("JAX_PLATFORMS", "(unset)"))
    print("XLA_FLAGS     =", os.environ.get("XLA_FLAGS", "(unset)"))
    # the ONE shared probe (bench.py probe_backend) so diagnose and the
    # bench driver always report the relay's state the same way
    from bench import probe_backend
    t0 = time.time()
    platform, kind = probe_backend(timeout=timeout, retries=1)
    dt = time.time() - t0
    if platform is not None:
        print("device 0 : %s (%s)  [%.1fs]" % (platform, kind, dt))
    else:
        print("probe TIMED OUT after %ds — backend init is wedged (if "
              "this host uses the axon TPU relay, that is the known "
              "failure mode; run CPU-only with JAX_PLATFORMS=cpu)"
              % timeout)


def check_env():
    section("MXTPU_* / MXNET_* environment")
    found = False
    for k in sorted(os.environ):
        if k.startswith(("MXTPU_", "MXNET_", "JAX_", "XLA_")):
            print("%s=%s" % (k, os.environ[k]))
            found = True
    if not found:
        print("(none set)")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=int, default=60,
                    help="backend probe timeout in seconds")
    ap.add_argument("--skip-backend", action="store_true",
                    help="skip the accelerator probe entirely")
    args = ap.parse_args()
    check_os()
    check_hardware()
    check_python()
    check_mxtpu()
    check_env()
    if not args.skip_backend:
        check_backend(args.timeout)


if __name__ == "__main__":
    main()
