"""Generate typed C++ Symbol wrappers for every registered operator.

Reference counterpart: cpp-package/OpWrapperGenerator.py — there it parses
the C API's op signatures (MXSymbolGetAtomicSymbolInfo) and emits op.h; here
we introspect the Python registry directly (the registry is the single
source of truth for both frontends) and emit include/mxtpu-cpp/op.hpp.

Usage: python tools/gen_cpp_op_wrappers.py  (rewrites op.hpp in place)
"""
from __future__ import annotations

import inspect
import keyword
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Optional *array* inputs (default None in the op fn but an NDArray/Symbol
# input, not a static param). Everything else defaulting to None is a param.
OPT_INPUTS = {
    "bias", "gamma", "beta", "moving_mean", "moving_var", "sequence_length",
    "state_cell", "crop_like", "trans", "grid", "label", "weight32",
    "data_lengths", "label_lengths",
}

# C++ reserved words that appear as op names or arg names
RESERVED = {"float", "double", "int", "bool", "operator", "new", "delete",
            "default", "template", "register", "union"}


def cpp_ident(name):
    if name in RESERVED or keyword.iskeyword(name):
        return name + "_"
    return name


def cpp_op_name(name):
    """Op name -> C++ function name (strip leading underscores of private
    namespaces; the reference capitalizes similarly in op.h)."""
    out = name.lstrip("_")
    out = out.replace(".", "_")
    return cpp_ident(out)


def param_decl(pname, default):
    """Map a python default value to a (c++ type, default literal) pair.

    All params cross the ABI as dmlc-style strings; typed C++ arguments are
    formatted by fmt_expr below.
    """
    pname = cpp_ident(pname)
    if isinstance(default, bool):
        return "bool", "true" if default else "false"
    if isinstance(default, int):
        return "int", str(default)
    if isinstance(default, float):
        v = repr(default)
        return "double", v
    if isinstance(default, str):
        return "const std::string &", '"%s"' % default
    if isinstance(default, tuple):
        return "Tuple", "Tuple{%s}" % ", ".join(repr(float(x))
                                                for x in default)
    if default is None:
        # stringly-typed escape hatch; "None" means "leave at op default"
        return "const std::string &", '"None"'
    raise TypeError("unmapped default %r for %s" % (default, pname))


def fmt_expr(pname, ctype):
    pname = cpp_ident(pname)
    if ctype == "bool":
        return '(%s ? "true" : "false")' % pname
    if ctype == "Tuple":
        return "TupleStr(%s)" % pname
    if ctype.startswith("const std::string"):
        return pname
    if ctype == "double":
        # std::to_string fixes 6 decimal places: to_string(1e-7) is
        # "0.000000", which would silently zero a scalar operand
        # (e.g. op::mul_scalar's multiplier). NumStr round-trips.
        return "NumStr(%s)" % pname
    return "std::to_string(%s)" % pname


def gen_op(name, op):
    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return None
    inputs, opt_inputs, params = [], [], []
    varargs = None
    for pname, p in sig.parameters.items():
        if pname.startswith("_"):
            continue
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            varargs = pname
        elif p.kind == inspect.Parameter.VAR_KEYWORD:
            continue
        elif p.default is inspect.Parameter.empty:
            inputs.append(pname)
        elif p.default is None and pname in OPT_INPUTS:
            opt_inputs.append(pname)
        else:
            params.append((pname, p.default))

    fn_name = cpp_op_name(name)
    args = ["const std::string &name"]
    if varargs:
        args.append("const std::vector<Symbol> &%s" % cpp_ident(varargs))
    args += ["Symbol %s" % cpp_ident(i) for i in inputs]
    body_params = []
    for pname, default in params:
        try:
            ctype, dflt = param_decl(pname, default)
        except TypeError:
            return None  # unmappable op: callers use Operator directly
        sep = " " if ctype.endswith("&") else " "
        args.append("%s%s%s = %s" % (ctype, sep, cpp_ident(pname), dflt))
        body_params.append((pname, ctype))
    args += ["Symbol %s = Symbol()" % cpp_ident(i) for i in opt_inputs]

    lines = []
    lines.append("inline Symbol %s(%s) {" % (fn_name, ",\n    ".join(args)))
    lines.append('  Operator op("%s");' % name)
    for pname, ctype in body_params:
        if ctype.startswith("const std::string"):
            # "None" sentinel: leave the op's own default in place
            lines.append('  if (%s != "None") op.SetParam("%s", %s);'
                         % (cpp_ident(pname), pname,
                            fmt_expr(pname, ctype)))
        else:
            lines.append('  op.SetParam("%s", %s);'
                         % (pname, fmt_expr(pname, ctype)))
    if varargs:
        lines.append("  for (const auto &s : %s) op.PushInput(s);"
                     % cpp_ident(varargs))
    for i in inputs:
        lines.append('  op.SetInput("%s", %s);' % (i, cpp_ident(i)))
    for i in opt_inputs:
        lines.append("  if (!%s.IsNull()) op.SetInput(\"%s\", %s);"
                     % (cpp_ident(i), i, cpp_ident(i)))
    lines.append("  return op.CreateSymbol(name);")
    lines.append("}")
    return "\n".join(lines)


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mxtpu.ops import registry

    seen = {}
    for n in registry.list_ops():
        op = registry.get_op(n)
        seen.setdefault(op.name, op)

    out = []
    out.append("""\
/* GENERATED by tools/gen_cpp_op_wrappers.py — do not edit by hand.
 *
 * Typed Symbol-building wrappers for every registered operator, generated
 * from the op registry the same way the reference's OpWrapperGenerator.py
 * generates cpp-package/include/mxnet-cpp/op.h from its C API. Ops whose
 * signatures cannot be typed (var-keyword params) are reachable through
 * the generic Operator class instead.
 */
#ifndef MXTPU_CPP_OP_HPP_
#define MXTPU_CPP_OP_HPP_

#include <string>
#include <vector>

#include "base.hpp"
#include "operator.hpp"
#include "symbol.hpp"

namespace mxtpu {
namespace cpp {
namespace op {
""")
    skipped = []
    for name in sorted(seen):
        code = gen_op(name, seen[name])
        if code is None:
            skipped.append(name)
            continue
        out.append(code)
        out.append("")
    out.append("}  // namespace op")
    out.append("}  // namespace cpp")
    out.append("}  // namespace mxtpu")
    out.append("")
    out.append("#endif  // MXTPU_CPP_OP_HPP_")
    dest = os.path.join(os.path.dirname(__file__), "..", "include",
                        "mxtpu-cpp", "op.hpp")
    with open(dest, "w") as f:
        f.write("\n".join(out))
    print("wrote %s: %d wrappers, %d skipped (%s)"
          % (dest, len(seen) - len(skipped), len(skipped),
             ", ".join(skipped)))


if __name__ == "__main__":
    main()
