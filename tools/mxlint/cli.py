"""mxlint command line.

Typical uses (from the repo root)::

    python tools/mxlint.py mxtpu tools        # lint, fail on findings
    python tools/mxlint.py mxtpu --baseline ci/mxlint_baseline.json
    python tools/mxlint.py mxtpu tools --write-baseline
    python tools/mxlint.py --diff             # only files changed vs main
    python tools/mxlint.py mxtpu --json out.json --passes lock-order

Exit status: 0 clean (or everything grandfathered/pragma'd), 1 findings
outside the baseline, 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import subprocess
import sys

from .core import (all_passes, diff_against_baseline, load_baseline,
                   run_paths, save_baseline)

DEFAULT_BASELINE = "ci/mxlint_baseline.json"
DEFAULT_PATHS = ("mxtpu", "tools")


def repo_root(start=None):
    p = pathlib.Path(start or __file__).resolve()
    for cand in [p] + list(p.parents):
        if (cand / ".git").exists() or (cand / "ROADMAP.md").exists():
            return cand
    return pathlib.Path.cwd()


def changed_files(root, base="main", paths=DEFAULT_PATHS):
    """Python files under ``paths`` changed vs ``base`` (committed diff
    + working tree), for the fast local ``--diff`` mode."""
    names = set()
    for cmd in (["git", "diff", "--name-only", base],
                ["git", "diff", "--name-only"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            out = subprocess.run(cmd, cwd=str(root), capture_output=True,
                                 text=True, timeout=30, check=False)
        except (OSError, subprocess.TimeoutExpired):
            continue
        names.update(ln.strip() for ln in out.stdout.splitlines()
                     if ln.strip())
    files = []
    for name in sorted(names):
        if not name.endswith(".py"):
            continue
        if not any(name == p or name.startswith(p.rstrip("/") + "/")
                   for p in paths):
            continue
        f = root / name
        if f.exists():
            files.append(f)
    return files


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: %s)"
                         % " ".join(DEFAULT_PATHS))
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="grandfather findings recorded in FILE "
                         "(default: %s when it exists)"
                         % DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: report everything")
    ap.add_argument("--write-baseline", nargs="?", const=True,
                    default=None, metavar="FILE",
                    help="write the current findings as the new "
                         "baseline (default file: %s)" % DEFAULT_BASELINE)
    ap.add_argument("--json", default=None, metavar="FILE",
                    help="write the findings artifact as JSON")
    ap.add_argument("--sarif", default=None, metavar="FILE",
                    help="write the findings artifact as SARIF 2.1.0 "
                         "(for CI diff annotation)")
    ap.add_argument("--lock-model", default=None, metavar="FILE",
                    help="write the static lockset model (guarded "
                         "shared attributes + their lock declaration "
                         "sites) for the runtime lock witness")
    ap.add_argument("--diff", nargs="?", const="main", default=None,
                    metavar="BASE",
                    help="lint only files changed vs BASE (default "
                         "main) — fast local pre-commit mode")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass subset (see "
                         "--list-passes)")
    ap.add_argument("--list-passes", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only the summary line")
    args = ap.parse_args(argv)

    if args.list_passes:
        for name, cls in sorted(all_passes().items()):
            print("%-18s %s" % (name, cls.description))
        return 0

    root = repo_root()
    pass_names = [p.strip() for p in args.passes.split(",")
                  if p.strip()] if args.passes else None

    files = None
    paths = [root / p for p in (args.paths or DEFAULT_PATHS)]
    if args.diff is not None:
        files = changed_files(root, base=args.diff,
                              paths=args.paths or DEFAULT_PATHS)
        if not files:
            print("mxlint: no changed python files vs %s" % args.diff)
            return 0

    findings = run_paths(paths, root=root, pass_names=pass_names,
                         files=files)

    if args.lock_model:
        from .core import build_project
        from .locksets import lockset_model
        model = lockset_model(build_project(paths, root, files=files))
        with open(args.lock_model, "w") as f:
            json.dump(model.witness_model(), f, indent=1,
                      sort_keys=True)
            f.write("\n")

    if args.write_baseline is not None:
        target = pathlib.Path(
            args.write_baseline if args.write_baseline is not True
            else root / DEFAULT_BASELINE)
        save_baseline(target, findings)
        print("mxlint: baseline with %d finding(s) written to %s"
              % (len(findings), target))
        return 0

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline and \
            (root / DEFAULT_BASELINE).exists():
        baseline_path = root / DEFAULT_BASELINE
    if baseline_path is not None and not args.no_baseline:
        baseline = load_baseline(baseline_path)
        new, old, stale = diff_against_baseline(findings, baseline)
    else:
        new, old, stale = findings, [], []

    if args.sarif:
        from .sarif import write_sarif
        write_sarif(args.sarif, findings,
                    baseline_fingerprints=[f.fingerprint for f in old])

    if args.json:
        doc = {"version": 1,
               "passes": sorted(pass_names or all_passes()),
               "counts": {"new": len(new), "grandfathered": len(old),
                          "stale_baseline": len(stale)},
               "findings": [f.to_dict() for f in new],
               "grandfathered": [f.to_dict() for f in old],
               "stale_baseline": stale}
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")

    if not args.quiet:
        for f in new:
            print("%s:%d: [%s] %s" % (f.path, f.line, f.pass_id,
                                      f.message))
            if f.text:
                print("    %s" % f.text)
    if stale and not args.quiet:
        print("mxlint: %d baseline entr%s no longer observed (fixed or "
              "drifted) — regenerate with --write-baseline to prune"
              % (len(stale), "y is" if len(stale) == 1 else "ies are"))
    print("mxlint: %d new finding(s), %d grandfathered, %d file(s)"
          % (len(new), len(old),
             len(files) if files is not None else
             sum(1 for _ in _count_files(paths))))
    if new:
        print("fix it, bless it with `# mxlint: allow(<pass>) — "
              "<reason>`, or (for pre-existing debt only) regenerate "
              "the baseline. docs/static_analysis.md has the workflow.")
    return 1 if new else 0


def _count_files(paths):
    from .core import iter_py_files
    return iter_py_files(paths)


if __name__ == "__main__":
    sys.exit(main())
