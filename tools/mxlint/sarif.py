"""SARIF 2.1.0 output: the findings artifact in the interchange format
CI diff-annotators understand (one run, one rule per pass, one result
per finding, the line-number-free fingerprint carried as a partial
fingerprint so annotations survive rebases the same way the baseline
does)."""
from __future__ import annotations

import json

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def findings_to_sarif(findings, tool_version="2.0",
                      baseline_fingerprints=()):
    """One SARIF ``run`` for a findings list. Findings whose
    fingerprint sits in ``baseline_fingerprints`` are marked
    ``baselineState: unchanged`` so annotators can hide them."""
    from .core import all_passes
    grandfathered = set(baseline_fingerprints)
    rules = []
    for name, cls in sorted(all_passes().items()):
        rules.append({
            "id": name,
            "shortDescription": {"text": cls.description or name},
            "helpUri": "docs/static_analysis.md",
        })
    results = []
    for f in findings:
        res = {
            "ruleId": f.pass_id,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col + 1)},
                },
                "logicalLocations": [{"name": f.func,
                                      "kind": "function"}],
            }],
        }
        if f.fingerprint:
            res["partialFingerprints"] = {
                "mxlint/v1": f.fingerprint}
        if f.fingerprint in grandfathered:
            res["baselineState"] = "unchanged"
        results.append(res)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "mxlint",
                "version": tool_version,
                "informationUri": "docs/static_analysis.md",
                "rules": rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def write_sarif(path, findings, baseline_fingerprints=()):
    doc = findings_to_sarif(
        findings, baseline_fingerprints=baseline_fingerprints)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc
