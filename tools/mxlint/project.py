"""Whole-program analysis context: the project symbol table, import
resolution, attribute-type inference, the cross-module call graph and
thread entry points — the infrastructure the interprocedural passes
(lock-order, wire-protocol, fault-coverage, env-drift) run on.

Scope model
-----------
``run_paths`` builds ONE :class:`Project` per lint invocation:

* When every requested file lives under the default lint roots
  (``mxtpu/``, ``tools/``), the project is the FULL tree under those
  roots and the requested files merely select which findings are
  *reported* — so ``--diff`` and single-file lints still see the whole
  call graph (a changed file's finding can depend on an unchanged
  peer).
* Otherwise (fixture corpora, tmp files) the project is exactly the
  requested file set. A request that named a *directory* is treated as
  a **closed** corpus: project-wide contract directions (a documented
  knob with no read site, a dispatched op nobody requests, an untested
  fault point) are meaningful and enabled. A request for loose files
  is open: only code-anchored directions run.

Resolution model (deliberately modest — precision over reach)
-------------------------------------------------------------
* ``self.m()``                 -> method ``m`` of the enclosing class,
  then of its single-inheritance bases known to the project.
* ``self.attr.m()``            -> via attribute-type inference:
  ``self.attr = Cls(...)`` anywhere in the class binds ``attr: Cls``.
* ``mod.f()`` / ``f()``        -> through the per-module import map
  (``import x.y as mod`` / ``from x.y import f``), else same-module
  top-level ``f``, else a project-wide *unique* name.
* Names shared with threading/queue primitives (``get``, ``wait``,
  ``join``...) never resolve through the unique-name fallback — a
  ``cv.wait()`` must not alias an unrelated method (see
  ``GENERIC_NAMES``).

Thread entry points — ``threading.Thread(target=f)``, pool
``submit(f)``, ``start_new_thread(f)`` — are indexed because they are
the concurrency roots: every lock-order cycle needs at least two of
them alive, and the fixture corpus seeds its cross-module inversion
through one.
"""
from __future__ import annotations

import ast
import pathlib
import re

# method names shared with the stdlib threading/queue/socket surface: a
# call like ``cv.wait()`` must never resolve to a same-named project
# method through the unique-name fallback (it would fabricate edges)
GENERIC_NAMES = frozenset((
    "wait", "join", "get", "put", "set", "clear", "notify",
    "notify_all", "acquire", "release", "is_set", "result",
    "append", "pop", "items", "values", "keys", "update", "add",
    "discard", "remove", "copy", "close", "start", "stop", "run",
    "send", "recv", "read", "write", "flush", "next", "reset",
    "submit", "shutdown", "cancel", "count", "index", "sort",
    "extend", "insert", "format", "strip", "split", "lower", "upper"))

DEFAULT_ROOT_DIRS = ("mxtpu", "tools")


class FuncRec:
    """One project function/method: where it is, who encloses it, what
    it calls (recorded by passes or the shared harvest below)."""

    __slots__ = ("relpath", "qualname", "node", "cls", "calls")

    def __init__(self, relpath, qualname, node, cls):
        self.relpath = relpath
        self.qualname = qualname
        self.node = node
        self.cls = cls              # enclosing class name or None
        self.calls = []             # [CallSite]

    @property
    def key(self):
        return (self.relpath, self.qualname)


class CallSite:
    """One call expression, pre-digested for resolution: ``kind`` is
    how the callee was named —

    * ``("plain", f)``          for ``f(...)``
    * ``("self", m)``           for ``self.m(...)``
    * ``("self_attr", a, m)``   for ``self.a.m(...)``
    * ``("name", n, m)``        for ``n.m(...)`` (n a local/imported
      name)
    * ``("other", m)``          for any deeper attribute chain
    """

    __slots__ = ("kind", "lineno")

    def __init__(self, kind, lineno):
        self.kind = kind
        self.lineno = lineno


def classify_call(call):
    """The :class:`CallSite` kind tuple for one ``ast.Call``, or None
    for calls through subscripts/calls/lambdas."""
    f = call.func
    if isinstance(f, ast.Name):
        return ("plain", f.id)
    if not isinstance(f, ast.Attribute):
        return None
    base = f.value
    if isinstance(base, ast.Name):
        if base.id == "self":
            return ("self", f.attr)
        return ("name", base.id, f.attr)
    if isinstance(base, ast.Attribute) and \
            isinstance(base.value, ast.Name) and base.value.id == "self":
        return ("self_attr", base.attr, f.attr)
    return ("other", f.attr)


class ClassRec:
    __slots__ = ("relpath", "name", "node", "bases", "methods",
                 "attr_types")

    def __init__(self, relpath, name, node):
        self.relpath = relpath
        self.name = name
        self.node = node
        self.bases = []             # base-class bare names
        self.methods = {}           # method name -> qualname
        self.attr_types = {}        # self.X = Cls(...) -> "Cls"


_THREAD_CTORS = frozenset(("Thread", "Timer"))
_SUBMIT_NAMES = frozenset(("submit", "start_new_thread",
                           "apply_async", "map_async"))


class Project:
    """The whole-program context handed to ``scope = "project"``
    passes."""

    def __init__(self, modules, root, closed=False, report_relpaths=None):
        self.root = pathlib.Path(root)
        self.modules = {}            # relpath -> ModuleInfo
        for m in modules:
            self.modules[m.relpath] = m
        self.closed = closed
        self.report_relpaths = set(report_relpaths) \
            if report_relpaths is not None else set(self.modules)
        self.funcs = {}              # (relpath, qualname) -> FuncRec
        self.classes = {}            # bare name -> [ClassRec]
        self.by_method = {}          # meth name -> [(relpath, qual, cls)]
        self.by_plain = {}           # fn name -> [(relpath, qual)]
        self.module_plain = {}       # (relpath, fn name) -> qual
        self.imports = {}            # relpath -> {local: ("module", rel)
        #                                        | ("symbol", rel, name)}
        self.entry_points = []       # [(relpath, qualname, lineno, how)]
        self._modname_to_rel = {}
        basenames = {}
        for relpath in self.modules:
            name = self._modname(relpath)
            self._modname_to_rel[name] = relpath
            basenames.setdefault(name.rsplit(".", 1)[-1],
                                 []).append(relpath)
        # a flat corpus imports by basename (``import beta``): register
        # unique basenames that no dotted name already claims
        for base, rels in basenames.items():
            if len(rels) == 1 and base not in self._modname_to_rel:
                self._modname_to_rel[base] = rels[0]
        for relpath, module in sorted(self.modules.items()):
            if module.tree is not None:
                self._harvest(relpath, module)
        self._resolve_entry_points()

    # -- construction ------------------------------------------------------
    @staticmethod
    def _modname(relpath):
        parts = pathlib.PurePosixPath(relpath).with_suffix("").parts
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def _harvest(self, relpath, module):
        self.imports[relpath] = imap = {}
        tree = module.tree
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    tgt = self._modname_to_rel.get(a.name)
                    if tgt is not None:
                        imap[a.asname or a.name.split(".")[0]] = \
                            ("module", tgt)
            elif isinstance(node, ast.ImportFrom):
                base = self._absolute_from(relpath, node)
                if base is None:
                    continue
                for a in node.names:
                    as_mod = self._modname_to_rel.get(
                        base + "." + a.name if base else a.name)
                    if as_mod is not None:
                        imap[a.asname or a.name] = ("module", as_mod)
                        continue
                    src = self._modname_to_rel.get(base)
                    if src is not None:
                        imap[a.asname or a.name] = \
                            ("symbol", src, a.name)
        # classes, functions, attribute types
        parents = module.parent_map()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                rec = ClassRec(relpath, node.name, node)
                rec.bases = [b.id if isinstance(b, ast.Name) else b.attr
                             for b in node.bases
                             if isinstance(b, (ast.Name, ast.Attribute))]
                self.classes.setdefault(node.name, []).append(rec)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual = module.qualname(node)
            cls = self._enclosing_class_name(parents, node)
            rec = FuncRec(relpath, qual, node, cls)
            self.funcs[rec.key] = rec
            if cls:
                self.by_method.setdefault(node.name, []).append(
                    (relpath, qual, cls))
                for crec in self.classes.get(cls, ()):
                    if crec.relpath == relpath:
                        crec.methods[node.name] = qual
            else:
                self.by_plain.setdefault(node.name, []).append(
                    (relpath, qual))
                cur = parents.get(node)
                if not isinstance(cur, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef)):
                    self.module_plain[(relpath, node.name)] = qual
            self._harvest_calls(rec)
        self._harvest_attr_types(relpath, module, parents)

    def _absolute_from(self, relpath, node):
        """Absolute module name a ``from X import ...`` refers to, or
        None when it points outside the project."""
        if node.level == 0:
            return node.module if node.module else None
        pkg = self._modname(relpath).split(".")
        # one level strips the module name itself, further levels strip
        # packages
        if len(pkg) < node.level:
            return None
        pkg = pkg[:len(pkg) - node.level]
        if node.module:
            pkg = pkg + node.module.split(".")
        return ".".join(pkg)

    @staticmethod
    def _enclosing_class_name(parents, node):
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a def nested in a method belongs to no class
                return None
            cur = parents.get(cur)
        return None

    def _harvest_calls(self, rec):
        for child in ast.walk(rec.node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and child is not rec.node:
                continue
            if isinstance(child, ast.Call):
                kind = classify_call(child)
                if kind is not None:
                    rec.calls.append(CallSite(kind, child.lineno))

    @staticmethod
    def _ctor_call(value):
        """The ``Cls(...)`` call in a binding value — direct, the
        fallback arm of ``self.x = given or Cls(...)``, or either arm
        of ``self.x = given if cond else Cls(...)``."""
        arms = (value,)
        if isinstance(value, ast.BoolOp) and isinstance(value.op,
                                                       ast.Or):
            arms = tuple(value.values)
        elif isinstance(value, ast.IfExp):
            arms = (value.body, value.orelse)
        for arm in arms:
            if isinstance(arm, ast.Call) and \
                    isinstance(arm.func, ast.Name):
                return arm
        return None

    def _harvest_attr_types(self, relpath, module, parents):
        """``self.X = Cls(...)`` inside a class binds ``X: Cls`` when
        ``Cls`` names a project class (possibly through an import);
        the ``self.X = given or Cls(...)`` default idiom binds too."""
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            v = self._ctor_call(node.value)
            if v is None:
                continue
            cname = v.func.id
            if cname not in self.classes and \
                    self.imports.get(relpath, {}).get(cname) is None:
                continue
            cls = self._enclosing_class_of_node(parents, node)
            if cls is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    for crec in self.classes.get(cls, ()):
                        if crec.relpath == relpath:
                            crec.attr_types[t.attr] = cname

    @staticmethod
    def _enclosing_class_of_node(parents, node):
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = parents.get(cur)
        return None

    def _resolve_entry_points(self):
        """Index ``Thread(target=f)`` / ``submit(f)`` /
        ``start_new_thread(f)`` spawn sites: the concurrency roots."""
        for relpath, module in sorted(self.modules.items()):
            if module.tree is None:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                fname = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                target = None
                if fname in _THREAD_CTORS:
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                elif fname in _SUBMIT_NAMES and node.args:
                    target = node.args[0]
                if target is None:
                    continue
                key = self._entry_target_key(relpath, module, node,
                                             target)
                if key is not None:
                    self.entry_points.append(
                        (key[0], key[1], node.lineno, fname))

    def _entry_target_key(self, relpath, module, call, target):
        encl = None
        parents = module.parent_map()
        cur = parents.get(call)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                encl = cur.name
                break
            cur = parents.get(cur)
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and encl:
            return self.resolve_method(encl, target.attr, relpath)
        if isinstance(target, ast.Name):
            got = self.resolve_plain(relpath, target.id)
            if got is not None:
                return got
        return None

    # -- resolution --------------------------------------------------------
    def resolve_method(self, cls, name, relpath=None):
        """``(relpath, qualname)`` of method ``name`` on class ``cls``
        (walking single-inheritance bases known to the project)."""
        seen = set()
        stack = [cls]
        while stack:
            cname = stack.pop(0)
            if cname in seen:
                continue
            seen.add(cname)
            recs = self.classes.get(cname, ())
            ordered = sorted(recs, key=lambda r: r.relpath != relpath)
            for crec in ordered:
                if name in crec.methods:
                    return (crec.relpath, crec.methods[name])
            for crec in ordered:
                stack.extend(crec.bases)
        return None

    def resolve_plain(self, relpath, name):
        """A bare-name call: same-module def, imported symbol, then a
        project-wide unique non-generic name."""
        got = self.module_plain.get((relpath, name))
        if got is not None:
            return (relpath, got)
        imp = self.imports.get(relpath, {}).get(name)
        if imp is not None and imp[0] == "symbol":
            tgt = self.module_plain.get((imp[1], imp[2]))
            if tgt is not None:
                return (imp[1], tgt)
            # an imported class: its __init__ runs at the call
            got = self.resolve_method(imp[2], "__init__", imp[1])
            if got is not None and imp[2] in self.classes:
                return got
        if name in self.classes:
            cands = self.classes[name]
            if len(cands) == 1:
                got = self.resolve_method(name, "__init__",
                                          cands[0].relpath)
                if got is not None:
                    return got
        if name in GENERIC_NAMES:
            return None
        cands = self.by_plain.get(name, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def resolve_callsite(self, relpath, caller_cls, kind):
        """Resolve one :class:`CallSite` kind tuple to a project
        function key, or None."""
        tag = kind[0]
        if tag == "plain":
            return self.resolve_plain(relpath, kind[1])
        if tag == "self":
            if caller_cls:
                got = self.resolve_method(caller_cls, kind[1], relpath)
                if got is not None:
                    return got
            return self._unique_method(kind[1])
        if tag == "self_attr":
            attr, meth = kind[1], kind[2]
            if caller_cls:
                for crec in self.classes.get(caller_cls, ()):
                    tname = crec.attr_types.get(attr)
                    if tname:
                        got = self.resolve_method(tname, meth,
                                                  crec.relpath)
                        if got is not None:
                            return got
            return self._unique_method(meth)
        if tag == "name":
            base, meth = kind[1], kind[2]
            imp = self.imports.get(relpath, {}).get(base)
            if imp is not None and imp[0] == "module":
                tgt = self.module_plain.get((imp[1], meth))
                if tgt is not None:
                    return (imp[1], tgt)
                # module.Class(...) constructor
                for crec in self.classes.get(meth, ()):
                    if crec.relpath == imp[1]:
                        return self.resolve_method(meth, "__init__",
                                                   imp[1])
                return None
            return self._unique_method(meth)
        if tag == "other":
            return self._unique_method(kind[1])
        return None

    def _unique_method(self, name):
        if name in GENERIC_NAMES:
            return None
        cands = self.by_method.get(name, [])
        if len(cands) == 1:
            return cands[0][:2]
        return None

    # -- contract context --------------------------------------------------
    def common_dir(self):
        dirs = [self.root / pathlib.Path(rel) for rel in self.modules]
        if not dirs:
            return self.root
        parts = None
        for d in dirs:
            p = d.parent.parts
            parts = p if parts is None else parts[
                :next((i for i, (a, b) in enumerate(zip(parts, p))
                       if a != b), min(len(parts), len(p)))]
        return pathlib.Path(*parts) if parts else self.root

    def find_contract_file(self, *relparts):
        """Walk up from the modules' common directory to the project
        root looking for e.g. ``docs/env_vars.md``; the fixture corpus
        carries its own copy below its corpus dir, the real tree
        resolves to the repo's."""
        cur = self.common_dir()
        root = self.root.resolve()
        while True:
            cand = cur.joinpath(*relparts)
            if cand.exists():
                return cand
            if cur.resolve() == root or cur.parent == cur:
                return None
            cur = cur.parent

    def contract_is_closed(self, contract_path):
        """Project-wide drift directions (dead doc entry, dead handler,
        untested fault point) fire only when the project can actually
        see every referent: the full default-roots tree, or a
        self-contained corpus whose contract file lives inside it."""
        if contract_path is None:
            return False
        if not self.closed:
            return False
        try:
            contract_path.resolve().relative_to(
                self.common_dir().resolve())
            return True
        except ValueError:
            pass
        # full-tree mode: the contract doc sits beside the roots
        return self._covers_default_roots()

    def _covers_default_roots(self):
        have = {rel.split("/")[0] for rel in self.modules}
        return set(DEFAULT_ROOT_DIRS) <= have

    def test_corpus(self):
        """``{relpath: text}`` of the sibling test tree (fault-matrix
        rows, env read sites in drivers) — reference material for the
        contract passes, never lint targets themselves."""
        tests = self.find_contract_file("tests")
        out = {}
        if tests is None or not tests.is_dir():
            return out
        for f in sorted(tests.rglob("*.py")):
            inner = f.relative_to(tests).parts
            if "__pycache__" in inner or "fixtures" in inner:
                continue
            try:
                rel = str(f.relative_to(self.root))
            except ValueError:
                rel = str(f)
            try:
                out[rel] = f.read_text(encoding="utf-8",
                                       errors="replace")
            except OSError:
                continue
        return out


_ENV_READ_RE = re.compile(
    r"""(?:environ(?:\.get|\.setdefault)?\s*[\[\(]\s*|getenv\s*\(\s*)
        ["'](MXTPU_[A-Z0-9_]+)["']""", re.VERBOSE)


def env_reads_in_text(text):
    """Textual env-read extraction for reference corpora (tests,
    examples) where a full AST pass would be overkill."""
    return set(_ENV_READ_RE.findall(text))
