"""mxlint — AST-based static analysis for the mxtpu concurrency,
host-sync and donation contracts.

``ci/check_robustness.py`` policed the dist/engine hot paths with line
regexes over a 3-line window plus a hand-pinned ALLOW list. That stops
working exactly where the code got dangerous: wrapped calls slip the
window, lock *nesting* is invisible to any line matcher, and the fused
train step's donation contract ("after the donating call, the old
buffers are dead until ``_data`` is rebound") is a dataflow property, not
a string. mxlint replaces the regex rules with real AST passes:

* ``blocking-call`` — unbounded ``recv``/``recv_into``/``wait``/``get``/
  ``join``/``create_connection``/``settimeout(None)`` detected on the
  call node, so wrapping and aliasing don't hide them.
* ``lock-order`` — per-function lock-acquisition graph (``with
  self._lock:`` nesting, ``acquire``/``release`` pairs, one-level-deep
  call summaries), reporting cycles and inconsistent acquisition orders
  as potential deadlocks.
* ``trace-purity`` — host syncs (``asnumpy``/``.item()``/``float()``/
  ``np.asarray``/``device_get``) and impure state writes inside
  functions reachable from a ``jax.jit`` root or the fused-step
  registration.
* ``use-after-donate`` — reads of an array passed at a donated argument
  position after the donating call, before it is rebound.
* ``except-swallow`` — ``except [Exception]: pass`` handlers, scoped by
  module criticality.

Deliberate cases are blessed IN THE SOURCE with an inline pragma::

    sock.recv_into(view)   # mxlint: allow(blocking-call) — reason here

and pre-existing findings are grandfathered via a committed baseline
(``ci/mxlint_baseline.json``): CI (``ci/check_static.py``) fails only on
findings that are neither pragma'd nor baselined. See
``docs/static_analysis.md`` for the pass catalog, the pragma grammar,
the baseline workflow and how to add a pass.
"""
from __future__ import annotations

from .core import (Finding, LintPass, ModuleInfo, all_passes, register,
                   run_paths)

__all__ = ["Finding", "LintPass", "ModuleInfo", "all_passes", "register",
           "run_paths"]

__version__ = "1.0"
