"""Flow-sensitive lockset dataflow core (mxlint v3).

The lock-order pass answers "can these locks deadlock"; this module
answers the complementary question the PR-12 failover replay bug made
urgent: **which locks are actually held when shared state is touched**.
It is the shared machinery under the ``shared-state-race`` (Eraser-style
lockset race detection) and ``blocking-under-lock`` passes, and the
exporter of the *static lock model* the runtime lock witness
(``mxtpu/devtools/lockwitness.py``) cross-checks in CI.

The model, in order of construction:

1. **Tokens.** A lock is named like the lock-order pass names it —
   class-scoped (``Cls._lock``), with the declaring class resolved
   through single-inheritance bases so ``Counter.inc``'s
   ``self._lock`` and ``Series.value``'s ``self._lock`` are ONE token
   (``Series._lock``). ``*lock_for*``/``*get_lock*`` factories collapse
   to one token per factory; bare local lock names scope to their
   function.

2. **Per-statement held-lockset walk.** Every function body is walked
   once tracking the held set through ``with`` items (nesting left to
   right), statement-level ``acquire()``/``release()`` pairs, and
   compound-statement bodies. At each interesting site the *current*
   held set is recorded: attribute accesses (read/write, including
   container mutation through ``self.x[k] = v`` and mutator-method
   calls like ``self.x.append(...)``), call sites (for the caller
   context and reachability), blocking calls, and thread-spawn points
   (for the init-phase exemption).

3. **Concurrency roots.** The entry points ``project.py`` already
   indexes (``Thread(target=)`` / ``submit`` / ``start_new_thread``),
   plus RPC dispatch handlers (detected structurally: a function
   assigning ``cmd``/``op`` from a frame's element 0 and comparing it
   against 2+ literals — the kvstore/serving local transport calls
   these on the *client's* thread, so they are roots even though the
   serve loop already reaches them), plus **main**: everything
   reachable from functions with no in-project callers that are not
   themselves spawn targets (the public API surface runs on the
   caller's thread).

4. **Effective locksets.** The lockset at a site is the directly held
   set union the function's *caller context*: the intersection of the
   held sets at every in-project call site resolving to it (one level
   — a same-class helper called only under ``self._lock`` inherits
   ``{Cls._lock}``; a root or an unlocked caller empties the context).

5. **Verdict.** An attribute with sites in >= 2 root contexts, at
   least one non-init write, and an EMPTY intersection of effective
   site locksets is a candidate race. Exemptions (documented in
   docs/static_analysis.md): init-phase writes (lexically before the
   first spawn point in ``__init__``, or in helpers called only from
   pre-spawn ``__init__`` code), lock-named guard attributes
   themselves, attributes bound to internally-synchronized types
   (Queue/Event/deque/obs registry series...), and obs metrics-plane
   instruments (``self.x = counter(...)`` / ``.labels(...)`` — their
   per-series locks are the guarantee, see obs/metrics.py).
"""
from __future__ import annotations

import ast
import pathlib
import re

from .project import classify_call

_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"))
_NAME_PAT = re.compile(r"lock|guard|mutex|cond|(^|_)cv$", re.IGNORECASE)
_FACTORY_PAT = re.compile(r"lock_for|get_lock", re.IGNORECASE)

# constructors whose instances carry their own synchronization (or are
# GIL-atomic for the single-op accesses this pass can see): binding one
# to an attribute makes method calls on that attribute safe without an
# explicit guard. Reassigning the binding itself post-init is still
# caught (the binding write is a plain attribute write... which this
# exemption removes; accepted noise/precision trade, documented).
_SYNCED_CTORS = frozenset((
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "Event", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "deque", "local", "ThreadPoolExecutor",
    "OrderedDict"))

# the obs metrics plane: instruments/series registered through these
# carry per-series locks (obs/metrics.py design rule #1) — state held
# in them is modeled by the registry, not by this pass
_OBS_CTORS = frozenset(("counter", "gauge", "histogram", "view",
                        "labels", "default", "Counter", "Gauge",
                        "Histogram"))

# container-mutator method names: a call like ``self.x.append(v)``
# writes x's state even though the AST marks the attribute Load
_MUTATORS = frozenset((
    "append", "appendleft", "add", "insert", "extend", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "sort", "reverse", "rotate"))

# blocking calls for the blocking-under-lock pass: socket waits,
# condition/event waits, queue hand-offs, joins, sleeps, future reads.
# ``send*`` is deliberately absent: a per-socket sender thread writing
# under its wire lock is the fleet's design, not a hazard.
_BLOCKING = frozenset(("recv", "recv_into", "accept", "connect",
                       "create_connection", "select", "wait",
                       "wait_for", "get", "put", "join", "sleep",
                       "result"))
_SPAWN_NAMES = frozenset(("start", "submit", "start_new_thread",
                          "apply_async", "map_async"))
_DISPATCH_VARS = frozenset(("cmd", "op", "command", "opcode"))


class AccessSite:
    """One attribute read/write with the locks held at it. ``kind`` is
    ``"read"``, ``"store"`` (plain rebind — GIL-atomic publication),
    ``"rmw"`` (AugAssign — a lost-update window even under the GIL) or
    ``"mut"`` (container mutation: subscript store/delete, mutator
    method call)."""

    __slots__ = ("attr_key", "kind", "relpath", "lineno", "func_key",
                 "held", "init_phase", "node")

    def __init__(self, attr_key, kind, relpath, lineno, func_key,
                 held, init_phase, node):
        self.attr_key = attr_key        # ((owner rel, owner cls), attr)
        self.kind = kind
        self.relpath = relpath
        self.lineno = lineno
        self.func_key = func_key        # (relpath, qualname)
        self.held = frozenset(held)
        self.init_phase = init_phase
        self.node = node

    @property
    def write(self):
        return self.kind != "read"


class BlockingSite:
    """One blocking call with the locks held around it."""

    __slots__ = ("name", "relpath", "lineno", "func_key", "held",
                 "wait_token", "node")

    def __init__(self, name, relpath, lineno, func_key, held,
                 wait_token, node):
        self.name = name
        self.relpath = relpath
        self.lineno = lineno
        self.func_key = func_key
        self.held = frozenset(held)
        self.wait_token = wait_token    # token waited ON (cv.wait)
        self.node = node


class _FuncLS:
    """Per-function lockset facts."""

    __slots__ = ("key", "relpath", "qualname", "cls", "node",
                 "accesses", "blocking", "callsites", "is_init",
                 "spawned", "self_thread_locals")

    def __init__(self, key, relpath, qualname, cls, node):
        self.key = key
        self.relpath = relpath
        self.qualname = qualname
        self.cls = cls
        self.node = node
        self.accesses = []        # [AccessSite]
        self.blocking = []        # [BlockingSite]
        self.callsites = []       # [(kind, lineno, frozenset(held))]
        self.is_init = qualname.endswith("__init__")
        self.spawned = False      # an __init__ that published self to
        #                           a thread it started
        self.self_thread_locals = set()   # locals bound to
        #                                   Thread(target=self.m)


class LocksetModel:
    """The whole-program lockset analysis; built once per lint run and
    shared by both passes (and the witness-model exporter) through
    :func:`lockset_model`."""

    def __init__(self, project):
        self.project = project
        self.lock_attrs = {}      # attr -> {(relpath, cls, lineno)}
        self.class_touch = {}     # (relpath, cls) -> touched attrs
        self.exempt_attrs = set()  # (ident, attr) synced/obs bindings
        self._token_idents = {}   # token label -> (ident, attr)
        self.funcs = {}           # func key -> _FuncLS
        self.roots = {}           # root id -> ("thread"|"dispatch", key)
        self._reach = {}          # root id -> set(func key)
        self._main_reach = None
        self._callers = None      # func key -> [(caller key, held)]
        self._ctx = {}            # func key -> frozenset (caller ctx)

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    def build(self):
        mods = sorted(self.project.modules.items())
        for _, module in mods:
            if module.tree is not None:
                self._collect_lock_attrs(module)
                self._collect_class_touch(module)
        for _, module in mods:
            if module.tree is not None:
                self._collect_exempt_attrs(module)
        for _, module in mods:
            if module.tree is not None:
                self._walk_module(module)
        self._collect_roots()
        return self

    def _collect_lock_attrs(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not (isinstance(value, ast.Call) and
                    isinstance(value.func, (ast.Attribute, ast.Name))):
                continue
            ctor = value.func.attr if isinstance(value.func,
                                                 ast.Attribute) \
                else value.func.id
            if ctor not in _LOCK_CTORS:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    cls = _enclosing_class(module, t)
                    self.lock_attrs.setdefault(t.attr, set()).add(
                        (module.relpath, cls or "?", node.lineno))

    def _collect_class_touch(self, module):
        """Which attrs each class touches in its own methods (for the
        base-class owner unification). Keyed by the class *identity*
        ``(relpath, name)`` — two modules' same-named classes are
        different classes (the profiler and the obs plane both have a
        ``Counter``)."""
        parents = module.parent_map()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Attribute):
                continue
            if not (isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            cur = parents.get(node)
            while cur is not None and not isinstance(cur, ast.ClassDef):
                cur = parents.get(cur)
            if cur is not None:
                self.class_touch.setdefault(
                    (module.relpath, cur.name), set()).add(node.attr)

    def _collect_exempt_attrs(self, module):
        """``self.x = Queue()`` / ``self.x = counter(...).labels(...)``
        — attributes bound to internally-synchronized objects."""
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not _is_synced_value(node.value):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    cls = _enclosing_class(module, t)
                    if cls:
                        owner = self.owner_class(cls, t.attr,
                                                 module.relpath)
                        self.exempt_attrs.add((owner, t.attr))

    # ------------------------------------------------------------------
    # owner-class unification
    # ------------------------------------------------------------------
    def _class_rec(self, cname, prefer_rel=None):
        """The :class:`ClassRec` for a bare name, preferring the
        same-module declaration (two modules' same-named classes must
        never merge)."""
        recs = self.project.classes.get(cname, ())
        if prefer_rel is not None:
            for r in recs:
                if r.relpath == prefer_rel:
                    return r
        return recs[0] if recs else None

    def owner_class(self, cls, attr, relpath):
        """Identity ``(relpath, name)`` of the most-base ancestor of
        ``cls`` (through single-inheritance bases known to the project)
        that touches ``attr`` — so ``Counter._value`` and
        ``Series._value`` are one attribute."""
        best = None
        for ident in self._base_chain(cls, relpath):
            if attr in self.class_touch.get(ident, ()):
                best = ident
        return best if best is not None else (relpath, cls)

    def _base_chain(self, cls, relpath):
        """Identities of ``cls`` and its ancestors, most-derived
        first."""
        chain, seen, stack = [], set(), [(cls, relpath)]
        while stack:
            cname, rel = stack.pop(0)
            rec = self._class_rec(cname, rel)
            if rec is None:
                ident = ("?", cname)
                if ident not in seen:
                    seen.add(ident)
                    chain.append(ident)
                continue
            ident = (rec.relpath, rec.name)
            if ident in seen:
                continue
            seen.add(ident)
            chain.append(ident)
            for b in rec.bases:
                stack.append((b, rec.relpath))
        return chain

    # ------------------------------------------------------------------
    # token naming
    # ------------------------------------------------------------------
    def token_for(self, expr, fls):
        """Lock token for an expression, or None when not lock-like."""
        cls = fls.cls
        if isinstance(expr, ast.Call):
            f = expr.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name and _FACTORY_PAT.search(name):
                if isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self" and cls:
                    return "%s.%s()" % (cls, name)
                return "?[%s].%s()" % (fls.relpath, name)
            return None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            declared = self.lock_attrs.get(attr)
            lockish = bool(declared) or bool(_NAME_PAT.search(attr))
            if not lockish:
                return None
            # ``self.shared.lock`` with ``self.shared = Shared(...)``
            # typed: the lock belongs to Shared — two classes guarding
            # through the same shared object must agree on the token
            base = expr.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and cls:
                for crec in self.project.classes.get(cls, ()):
                    if crec.relpath != fls.relpath:
                        continue
                    tname = crec.attr_types.get(base.attr)
                    if tname:
                        owner = self._lock_owner(tname, attr,
                                                 crec.relpath)
                        return self._token_label(owner, attr)
            root = _attr_chain_root(expr)
            if isinstance(root, ast.Name) and root.id == "self" and cls:
                owner = self._lock_owner(cls, attr, fls.relpath)
                return self._token_label(owner, attr)
            if declared:
                idents = {(rel, c) for (rel, c, _) in declared}
                if len(idents) == 1:
                    return self._token_label(next(iter(idents)), attr)
                local = {(rel, c) for (rel, c, _) in declared
                         if rel == fls.relpath}
                if len(local) == 1:
                    return self._token_label(next(iter(local)), attr)
            return "?[%s].%s" % (fls.relpath, attr)
        if isinstance(expr, ast.Name) and _NAME_PAT.search(expr.id):
            return "local[%s:%s].%s" % (fls.relpath, fls.qualname,
                                        expr.id)
        if isinstance(expr, ast.Subscript):
            return self.token_for(expr.value, fls)
        return None

    def _lock_owner(self, cls, attr, relpath):
        """Declaring class identity for a lock attr through the base
        chain — prefer a chain class that ASSIGNS the lock, else the
        deepest chain class touching it."""
        decl_idents = {(rel, c) for (rel, c, _)
                       in self.lock_attrs.get(attr, ())}
        owner = None
        for ident in self._base_chain(cls, relpath):
            if ident in decl_idents:
                owner = ident
        if owner is not None:
            return owner
        return self.owner_class(cls, attr, relpath)

    def _token_label(self, ident, attr):
        """Readable, identity-unique token string: ``Cls.attr`` when
        the bare class name is project-unique, else ``Cls[rel].attr``.
        The identity is remembered for :meth:`lock_decl_sites`."""
        rel, cls = ident
        if len(self.project.classes.get(cls, ())) > 1:
            label = "%s[%s].%s" % (cls, rel, attr)
        else:
            label = "%s.%s" % (cls, attr)
        self._token_idents[label] = (ident, attr)
        return label

    def lock_decl_sites(self, token):
        """``[(relpath, lineno)]`` where the lock behind ``token`` is
        created (for the runtime witness); [] for factory/local/unknown
        tokens."""
        got = self._token_idents.get(token)
        if got is None:
            return []
        (rel, cls), attr = got
        out = []
        for (drel, dcls, lineno) in self.lock_attrs.get(attr, ()):
            if (drel, dcls) == (rel, cls):
                out.append((drel, lineno))
        return sorted(out)

    # ------------------------------------------------------------------
    # the flow-sensitive walk
    # ------------------------------------------------------------------
    def _walk_module(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual = module.qualname(node)
            cls = _enclosing_class(module, node)
            fls = _FuncLS((module.relpath, qual), module.relpath, qual,
                          cls, node)
            self.funcs[fls.key] = fls
            self._walk_body(module, fls, node.body, [])

    def _walk_body(self, module, fls, body, held):
        for stmt in body:
            self._walk_stmt(module, fls, stmt, held)

    def _walk_stmt(self, module, fls, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                    # nested defs analyzed separately
        if isinstance(stmt, ast.With):
            pushed = []
            for item in stmt.items:
                self._scan_expr(module, fls, item.context_expr, held,
                                store_targets=())
                tok = self.token_for(item.context_expr, fls)
                if tok is not None:
                    held.append(tok)
                    pushed.append(tok)
            self._walk_body(module, fls, stmt.body, held)
            for tok in pushed:
                held.remove(tok)
            return
        call = _stmt_call(stmt)
        if call is not None and isinstance(call.func, ast.Attribute):
            if call.func.attr == "acquire":
                tok = self.token_for(call.func.value, fls)
                if tok is not None:
                    held.append(tok)
                    return
            elif call.func.attr == "release":
                tok = self.token_for(call.func.value, fls)
                if tok is not None and tok in held:
                    held.remove(tok)
                    return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Delete)):
            self._scan_assign(module, fls, stmt, held)
        else:
            for expr in _stmt_exprs(stmt):
                self._scan_expr(module, fls, expr, held,
                                store_targets=())
        # compound bodies recurse with the current held set
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self._walk_body(module, fls, sub, held)
        for h in getattr(stmt, "handlers", []) or []:
            self._walk_body(module, fls, h.body, held)
        # the guarded-acquire idiom: ``if not lock.acquire(...):
        # return`` — the fall-through path holds the lock from here on
        tok = self._guarded_acquire_token(fls, stmt)
        if tok is not None:
            held.append(tok)

    def _guarded_acquire_token(self, fls, stmt):
        """Token for ``if not X.acquire(...):`` whose body leaves the
        function (return/raise/continue/break) — after the statement
        the lock is held."""
        if not isinstance(stmt, ast.If) or stmt.orelse:
            return None
        test = stmt.test
        if not (isinstance(test, ast.UnaryOp)
                and isinstance(test.op, ast.Not)
                and isinstance(test.operand, ast.Call)
                and isinstance(test.operand.func, ast.Attribute)
                and test.operand.func.attr == "acquire"):
            return None
        if not stmt.body or not isinstance(
                stmt.body[-1], (ast.Return, ast.Raise, ast.Continue,
                                ast.Break)):
            return None
        return self.token_for(test.operand.func.value, fls)

    # -- expression scanning ----------------------------------------------
    def _scan_assign(self, module, fls, stmt, held):
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AugAssign):
            targets, value = [stmt.target], stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
            value = stmt.value
        else:                               # Delete
            targets, value = stmt.targets, None
        aug = isinstance(stmt, ast.AugAssign)
        # track ``t = Thread(target=self._loop)`` locals so a later
        # ``t.start()`` flips the init-phase latch
        if fls.is_init and isinstance(stmt, ast.Assign) and \
                isinstance(value, ast.Call):
            cname = value.func.attr \
                if isinstance(value.func, ast.Attribute) \
                else (value.func.id
                      if isinstance(value.func, ast.Name) else None)
            if cname in ("Thread", "Timer") and any(
                    isinstance(n, ast.Name) and n.id == "self"
                    for n in ast.walk(value)):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        fls.self_thread_locals.add(t.id)
        for t in targets:
            self._scan_target(module, fls, t, held, aug=aug)
        if value is not None:
            self._scan_expr(module, fls, value, held, store_targets=())

    def _scan_target(self, module, fls, target, held, aug=False):
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._scan_target(module, fls, e, held, aug=aug)
            return
        if isinstance(target, ast.Starred):
            self._scan_target(module, fls, target.value, held, aug=aug)
            return
        if isinstance(target, ast.Attribute):
            key = self._attr_key(fls, target)
            if key is not None:
                self._note_access(module, fls, key,
                                  "rmw" if aug else "store", target,
                                  held)
            # the chain below the written attr is read
            self._scan_expr(module, fls, target.value, held,
                            store_targets=())
            return
        if isinstance(target, ast.Subscript):
            # self.x[k] = v mutates x
            base = target.value
            if isinstance(base, ast.Attribute):
                key = self._attr_key(fls, base)
                if key is not None:
                    self._note_access(module, fls, key, "mut", base,
                                      held)
                self._scan_expr(module, fls, base.value, held,
                                store_targets=())
            else:
                self._scan_expr(module, fls, base, held,
                                store_targets=())
            self._scan_expr(module, fls, target.slice, held,
                            store_targets=())
            return
        # plain Name targets carry no attribute state

    def _scan_expr(self, module, fls, node, held, store_targets=()):
        """Record accesses / calls / blocking sites in one expression
        tree with the current held set."""
        for child in ast.walk(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                self._scan_call(module, fls, child, held)
            elif isinstance(child, ast.Attribute) and \
                    isinstance(child.ctx, ast.Load):
                if _is_mutator_receiver(module, child):
                    continue       # handled as a write by _scan_call
                parent = module.parent_map().get(child)
                if isinstance(parent, ast.Call) and \
                        parent.func is child and \
                        self._is_method_name(fls, child):
                    continue       # ``self.m(...)`` — a method, not state
                key = self._attr_key(fls, child)
                if key is not None:
                    self._note_access(module, fls, key, "read", child,
                                      held)

    def _scan_call(self, module, fls, call, held):
        kind = classify_call(call)
        if kind is not None:
            fls.callsites.append((kind, call.lineno, frozenset(held)))
        f = call.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name is None:
            return
        # the init-phase latch: flip once __init__ hands *self* to a
        # live thread — Thread(target=self.m).start(), a tracked
        # t = Thread(target=self._loop) local's .start(), a
        # self-attr thread's .start(), or submit(self.m). Starting an
        # unrelated component (ParameterServer(...).start()) does not
        # publish this object.
        if fls.is_init and not fls.spawned and name in _SPAWN_NAMES:
            if self._spawn_publishes_self(fls, call, name):
                fls.spawned = True
        # container mutators on an attribute are writes — unless the
        # receiver is a project class defining a method of that name
        # (``self._stats.add("k")`` is a call, not a set.add)
        if name in _MUTATORS and isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Attribute):
            if not self._is_method_name(fls, f):
                key = self._attr_key(fls, f.value)
                if key is not None:
                    self._note_access(module, fls, key, "mut", f.value,
                                      held)
        # blocking calls
        if name in _BLOCKING:
            site = self._blocking_site(module, fls, call, name, held)
            if site is not None:
                fls.blocking.append(site)

    @staticmethod
    def _spawn_publishes_self(fls, call, name):
        """Does this start/submit hand ``self`` (or a thread whose
        target is a self-method) to another thread?"""
        if any(isinstance(n, ast.Name) and n.id == "self"
               for n in ast.walk(call)):
            return True
        f = call.func
        if name == "start" and isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and \
                f.value.id in fls.self_thread_locals:
            return True
        return False

    def _blocking_site(self, module, fls, call, name, held):
        f = call.func
        if name == "get":
            # dict.get(key[, default]) carries positional args;
            # queue.get() / queue.get(timeout=...) does not
            if call.args:
                return None
            if not isinstance(f, ast.Attribute):
                return None
        if name in ("wait", "wait_for", "join", "result", "put") and \
                not isinstance(f, ast.Attribute):
            return None
        if name == "join" and call.args:
            return None      # os.path.join / "sep".join — not a wait
        wait_token = None
        if name in ("wait", "wait_for") and isinstance(f, ast.Attribute):
            wait_token = self.token_for(f.value, fls)
        return BlockingSite(name, module.relpath, call.lineno, fls.key,
                            held, wait_token, call)

    def _is_method_name(self, fls, node):
        """``self.m`` in call position where ``m`` is a known method of
        the class (or its bases): a method lookup, not a state read. A
        stored callable (``self._cb(...)``) stays a read."""
        base = node.value
        if isinstance(base, ast.Name) and base.id == "self" and fls.cls:
            return self.project.resolve_method(
                fls.cls, node.attr, fls.relpath) is not None
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and fls.cls:
            for crec in self.project.classes.get(fls.cls, ()):
                if crec.relpath != fls.relpath:
                    continue
                tname = crec.attr_types.get(base.attr)
                if tname:
                    return self.project.resolve_method(
                        tname, node.attr, crec.relpath) is not None
            # untyped receiver in plain call position: not a state
            # read; a MUTATOR name on an untyped receiver stays a
            # container mutation (the caller checks kind first)
            return node.attr not in _MUTATORS
        return True            # deeper chains are out of model anyway

    # -- attribute identity ------------------------------------------------
    def _attr_key(self, fls, node):
        """``(owner class, attr)`` for a ``self.X`` (or typed
        ``self.a.b``) attribute expression; None for everything this
        pass does not model."""
        if not isinstance(node, ast.Attribute):
            return None
        attr = node.attr
        if attr.startswith("__") or _NAME_PAT.search(attr):
            return None           # dunders and the guards themselves
        base = node.value
        if isinstance(base, ast.Name) and base.id == "self":
            if fls.cls is None:
                return None
            owner = self.owner_class(fls.cls, attr, fls.relpath)
            return (owner, attr)
        # one level through attribute types: self.a.b with a typed
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and fls.cls:
            for crec in self.project.classes.get(fls.cls, ()):
                if crec.relpath != fls.relpath:
                    continue
                tname = crec.attr_types.get(base.attr)
                if tname:
                    return (self.owner_class(tname, attr,
                                             crec.relpath), attr)
        return None

    def _note_access(self, module, fls, key, kind, node, held):
        init = fls.is_init and not fls.spawned
        fls.accesses.append(AccessSite(
            key, kind, module.relpath, node.lineno, fls.key, held,
            init, node))

    # ------------------------------------------------------------------
    # concurrency roots and reachability
    # ------------------------------------------------------------------
    def _collect_roots(self):
        for (relpath, qual, lineno, how) in self.project.entry_points:
            key = (relpath, qual)
            if key in self.funcs:
                self.roots.setdefault("thread:%s:%s" % key,
                                      ("thread", key))
        for key in self._dispatch_handlers():
            self.roots.setdefault("dispatch:%s:%s" % key,
                                  ("dispatch", key))

    def _dispatch_handlers(self):
        """Functions that structurally ARE frame dispatchers (the wire
        servers' per-op switch): roots because the local transport runs
        them on the requesting thread."""
        out = []
        for key, fls in self.funcs.items():
            dvars = set()
            for node in ast.walk(fls.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Subscript) and \
                        isinstance(node.value.slice, ast.Constant) and \
                        node.value.slice.value == 0:
                    for t in node.targets:
                        if isinstance(t, ast.Name) and \
                                t.id in _DISPATCH_VARS:
                            dvars.add(t.id)
            if not dvars:
                continue
            lits = set()
            for node in ast.walk(fls.node):
                if isinstance(node, ast.Compare) and \
                        len(node.ops) == 1 and \
                        isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                    for lit, var in ((node.left, node.comparators[0]),
                                     (node.comparators[0], node.left)):
                        if isinstance(var, ast.Name) and \
                                var.id in dvars and \
                                isinstance(lit, ast.Constant) and \
                                isinstance(lit.value, str):
                            lits.add(lit.value)
            if len(lits) >= 2:
                out.append(key)
        return sorted(out)

    def _call_edges(self, key):
        fls = self.funcs.get(key)
        if fls is None:
            return ()
        out = []
        for (kind, _lineno, _held) in fls.callsites:
            tgt = self.project.resolve_callsite(fls.relpath, fls.cls,
                                                kind)
            if tgt is not None and tgt in self.funcs:
                out.append(tgt)
        return out

    def _reach_from(self, keys):
        seen = set()
        stack = list(keys)
        while stack:
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            stack.extend(self._call_edges(k))
        return seen

    def reach(self, root_id):
        got = self._reach.get(root_id)
        if got is None:
            _, key = self.roots[root_id]
            got = self._reach_from([key])
            self._reach[root_id] = got
        return got

    def main_reach(self):
        """Everything reachable from the public surface: functions
        with no in-project callers that are not spawn targets or
        dispatchers — they run on whatever thread calls the API."""
        if self._main_reach is None:
            called = set()
            for key in self.funcs:
                called.update(self._call_edges(key))
            root_keys = {key for (_, key) in self.roots.values()}
            mains = [k for k in self.funcs
                     if k not in called and k not in root_keys]
            self._main_reach = self._reach_from(mains)
        return self._main_reach

    def contexts_of(self, func_key):
        """The concurrency roots whose reach includes ``func_key``
        (root ids, plus ``"main"``)."""
        out = set()
        for root_id in self.roots:
            if func_key in self.reach(root_id):
                out.add(root_id)
        if func_key in self.main_reach():
            out.add("main")
        return out

    # ------------------------------------------------------------------
    # caller context (one level)
    # ------------------------------------------------------------------
    def _caller_index(self):
        if self._callers is None:
            self._callers = {}
            for key, fls in self.funcs.items():
                for (kind, lineno, held) in fls.callsites:
                    tgt = self.project.resolve_callsite(
                        fls.relpath, fls.cls, kind)
                    if tgt is not None and tgt in self.funcs:
                        self._callers.setdefault(tgt, []).append(
                            (key, held))
        return self._callers

    def caller_ctx(self, func_key):
        """Locks guaranteed held on ENTRY to ``func_key``: the
        intersection over every in-project call site of (locks held at
        the site ∪ the caller's own entry context) — a transitive
        fixpoint, so the ``public() -> _locked() -> _helper()`` layering
        idiom keeps its lock through any helper depth. Empty for
        concurrency roots and public-surface functions (anyone may call
        those with nothing held)."""
        if not self._ctx:
            self._compute_ctxs()
        return self._ctx.get(func_key, frozenset())

    def _compute_ctxs(self):
        callers = self._caller_index()
        root_keys = {key for (_, key) in self.roots.values()}
        public = self._public_surface()
        fixed = {f for f in self.funcs
                 if f in root_keys or f in public or not callers.get(f)}
        TOP = None                  # optimistic "not yet known"
        ctx = {f: (frozenset() if f in fixed else TOP)
               for f in self.funcs}
        changed = True
        while changed:
            changed = False
            for f in self.funcs:
                if f in fixed:
                    continue
                cur = None
                for (c, held) in callers.get(f, ()):
                    cctx = ctx.get(c, frozenset())
                    if cctx is TOP:
                        continue          # back edge: resolve optimistically
                    v = held | cctx
                    cur = set(v) if cur is None else cur & v
                new = TOP if cur is None else frozenset(cur)
                if new != ctx[f]:
                    ctx[f] = new
                    changed = True
        self._ctx = {f: (v if v is not None else frozenset())
                     for f, v in ctx.items()}

    def _public_surface(self):
        """Function keys with no in-project callers (API surface)."""
        if not hasattr(self, "_public"):
            called = set()
            for key in self.funcs:
                called.update(self._call_edges(key))
            self._public = {k for k in self.funcs if k not in called}
        return self._public

    def effective(self, site):
        """held ∪ caller-context for one site."""
        return site.held | self.caller_ctx(site.func_key)

    # ------------------------------------------------------------------
    # verdicts
    # ------------------------------------------------------------------
    def attr_sites(self):
        """``{(cls, attr): [AccessSite]}`` over the whole project.
        Exempt attributes are removed, and so are sites blessed by a
        reasoned ``allow(shared-state-race)`` pragma: a blessed site is
        excluded from the MODEL, not merely from the report — a
        deliberate lifecycle writer (boot-time restore, demotion path)
        must not poison the lockset intersection and flag every other
        correctly-locked site of the attribute."""
        out = {}
        for fls in self.funcs.values():
            module = self.project.modules.get(fls.relpath)
            for site in fls.accesses:
                if site.attr_key in self.exempt_attrs:
                    continue
                if module is not None and module.pragmas.allows(
                        site.lineno, "shared-state-race"):
                    continue
                out.setdefault(site.attr_key, []).append(site)
        return out

    def shared_attrs(self):
        """``[(attr_key, sites, contexts, intersection)]`` for every
        attribute accessed from >= 2 concurrency roots with >= 1
        non-init write. ``intersection`` is the lockset common to every
        live (non-init) site — empty means candidate race."""
        out = []
        func_ctx = {}
        for attr_key, sites in sorted(self.attr_sites().items()):
            live = [s for s in sites if not s.init_phase]
            if not any(s.write for s in live):
                continue
            contexts = set()
            for s in live:
                ctx = func_ctx.get(s.func_key)
                if ctx is None:
                    ctx = self.contexts_of(s.func_key)
                    func_ctx[s.func_key] = ctx
                contexts |= ctx
            if len(contexts) < 2:
                continue
            inter = None
            for s in live:
                eff = self.effective(s)
                inter = set(eff) if inter is None else inter & eff
            out.append((attr_key, live, contexts,
                        frozenset(inter or ())))
        return out

    def races(self):
        """The reportable subset of :meth:`shared_attrs` — empty
        overall intersection AND one of three hazard shapes (each a
        genuine corruption window, not a GIL-atomic publication):

        (a) **unserialized writers** — >= 2 write sites with no lock
            common to all of them, at least one being locked or
            compound (two mutators of one map/counter that are not
            mutually excluded can interleave and lose an update);
        (b) **concurrent read-modify-write** — an unlocked ``+=`` /
            container mutation in a function reachable from >= 2
            concurrency roots (the load-op-store window loses updates
            even under the GIL);
        (c) **wrong-lock read** — the writers DO share a lock, but a
            read site holds only locks disjoint from it (the reader
            believes it is synchronized; it is not — it can see a
            half-updated structure mid-write).

        A flag that is only ever plainly rebound and read
        (``self.dying = True`` / ``if self.dying``) stays quiet: one
        bytecode op each way, atomic under the GIL, and the fleet's
        deliberate idiom. An unlocked *plain read* of locked state is
        likewise quiet — that is the snapshot-read idiom ``stats()``
        uses everywhere."""
        out = []
        for (attr_key, sites, contexts, inter) in self.shared_attrs():
            if inter:
                continue
            writes = [s for s in sites if s.write]
            w_inter = None
            w_union = set()
            for s in writes:
                eff = self.effective(s)
                w_inter = set(eff) if w_inter is None else w_inter & eff
                w_union |= eff
            w_inter = w_inter or set()
            locked_writes = any(self.effective(s) for s in writes)
            compound = any(s.kind in ("rmw", "mut") for s in writes)
            cand = (len(writes) >= 2 and not w_inter
                    and (locked_writes or compound))
            if not cand:
                cand = any(
                    s.kind in ("rmw", "mut") and not self.effective(s)
                    and len(self.contexts_of(s.func_key)) >= 2
                    for s in writes)
            if not cand and w_inter:
                cand = any(
                    not s.write and self.effective(s)
                    and not (self.effective(s) & w_inter)
                    for s in sites)
            if not cand:
                continue
            # the *offending* sites — where a pragma or a fix belongs:
            # every write when the writers share no lock, and the
            # wrong-lock readers (a reader holding only locks disjoint
            # from every writer's believes it is synchronized and is
            # not). A PLAIN unlocked read stays quiet either way —
            # that is the GIL-atomic snapshot-read idiom ``stats()``
            # uses everywhere, and the write side is where the
            # corruption happens.
            offending = []
            for s in sites:
                if s.write:
                    if not w_inter:
                        offending.append(s)
                else:
                    eff = self.effective(s)
                    if eff and not (eff & w_union):
                        offending.append(s)
            out.append((attr_key, sites, contexts, offending))
        return out

    def blocking_sites(self):
        """Every blocking call whose effective lockset is non-empty,
        excluding condition waits on a held token (the wait RELEASES
        that lock)."""
        out = []
        for fls in self.funcs.values():
            for site in fls.blocking:
                eff = site.held | self.caller_ctx(site.func_key)
                if site.wait_token is not None and \
                        site.wait_token in eff:
                    eff = eff - {site.wait_token}
                    if not eff:
                        continue
                    # waiting on one cv while holding ANOTHER lock
                    # still stalls that other lock's waiters
                if eff:
                    out.append((site, frozenset(eff)))
        return out

    # ------------------------------------------------------------------
    # the static lock model (runtime witness contract)
    # ------------------------------------------------------------------
    def witness_model(self):
        """JSON-ready model of every *guarded* shared attribute: the
        witness watches these at runtime and reports any shared access
        observed with no lock held — a static-model contradiction."""
        attrs = []
        for (attr_key, sites, contexts, inter) in self.shared_attrs():
            if not inter:
                continue              # candidate races, not guarded
            guards = []
            for tok in sorted(inter):
                decls = self.lock_decl_sites(tok)
                if decls:
                    guards.append({"token": tok,
                                   "decl": [list(d) for d in decls]})
            if not guards:
                continue              # factory/local guards: unwitnessable
            (rel, cls), attr = attr_key
            mod = _module_name(rel)
            if mod is None:
                continue
            attrs.append({
                "class": cls, "attr": attr, "module": mod,
                "guards": guards,
                "sites": len(sites),
                "contexts": sorted(contexts)})
        return {"version": 1, "attrs": attrs}


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _attr_chain_root(node):
    while isinstance(node, ast.Attribute):
        node = node.value
    return node


def _module_name(relpath):
    """Importable dotted module for an ``mxtpu/`` relpath (the runtime
    witness imports it); None for ``tools/`` scripts — those are not
    importable packages."""
    rel = pathlib.PurePosixPath(relpath)
    if not rel.parts or rel.parts[0] != "mxtpu":
        return None
    parts = rel.with_suffix("").parts
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _enclosing_class(module, node):
    """Class owning ``node``. For a ``def`` node: its syntactic class
    (None when nested inside a method). For anything else (an
    attribute site): the nearest enclosing class — a closure inside a
    method still sees the method's ``self``."""
    parents = module.parent_map()
    cur = parents.get(node)
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
            cur = parents.get(cur)
        return None
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = parents.get(cur)
    return None


def _stmt_call(stmt):
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        return stmt.value
    return None


def _stmt_exprs(stmt):
    """The expression children of a statement, excluding compound
    bodies (those recurse with their own held set)."""
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v


def _is_mutator_receiver(module, attr_node):
    """True when this Load attribute is the receiver of a mutator call
    (``self.x.append(...)`` — x is recorded as a write, not a read)."""
    parent = module.parent_map().get(attr_node)
    return (isinstance(parent, ast.Attribute)
            and parent.attr in _MUTATORS
            and isinstance(module.parent_map().get(parent), ast.Call)
            and module.parent_map().get(parent).func is parent)


def _is_synced_value(value):
    """Value expression constructing an internally-synchronized object
    (possibly through a dotted name or a trailing ``.labels(...)``)."""
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name in _SYNCED_CTORS or name in _OBS_CTORS:
        return True
    # chained obs idiom: counter("a.b").labels("x") — func is an
    # Attribute on a Call
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Call):
        return _is_synced_value(f.value)
    return False


_MODEL_CACHE = {}


def lockset_model(project):
    """The per-project singleton: both passes (and the CLI's
    ``--lock-model`` exporter) share one built analysis."""
    key = id(project)
    got = _MODEL_CACHE.get(key)
    if got is None or got[0] is not project:
        model = LocksetModel(project).build()
        _MODEL_CACHE.clear()      # one project per run; never grow
        _MODEL_CACHE[key] = (project, model)
        return model
    return got[1]
