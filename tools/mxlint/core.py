"""mxlint framework core: findings, the pass registry, per-module
analysis context (AST + pragmas), baseline bookkeeping and the runner.

Design notes
------------
* A **finding** anchors at the AST node's first line. Pragmas and the
  baseline both key off that anchor, so a wrapped multi-line call is
  suppressed at the line the call *starts* on — no 3-line windows.
* **Pragmas** are parsed from real COMMENT tokens (``tokenize``), never
  from string literals. A pragma on a ``def``/``class`` header line
  covers the whole body; anywhere else it covers its own line, and a
  comment-only line covers the next code line.
* The **baseline** stores content fingerprints, not line numbers:
  ``(path, pass, enclosing-qualname, stripped source line, occurrence
  index)``. Moving a grandfathered offender around a file does not
  un-grandfather it; editing or duplicating it does — which is the
  point.
"""
from __future__ import annotations

import ast
import hashlib
import io
import json
import pathlib
import tokenize


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------

class Finding:
    """One diagnostic: where, which pass, what, and in which function."""

    __slots__ = ("path", "line", "col", "pass_id", "message", "text",
                 "func", "fingerprint")

    def __init__(self, path, line, col, pass_id, message, text="",
                 func="<module>"):
        self.path = str(path)
        self.line = int(line)
        self.col = int(col)
        self.pass_id = pass_id
        self.message = message
        self.text = text
        self.func = func
        self.fingerprint = None     # assigned by assign_fingerprints

    def sort_key(self):
        return (self.path, self.line, self.col, self.pass_id,
                self.message)

    def to_dict(self):
        return {"fingerprint": self.fingerprint, "path": self.path,
                "line": self.line, "pass": self.pass_id,
                "func": self.func, "text": self.text,
                "message": self.message}

    def __repr__(self):
        return "%s:%d: [%s] %s" % (self.path, self.line, self.pass_id,
                                   self.message)


def assign_fingerprints(findings):
    """Stable content fingerprints, line-number free. Identical
    (path, pass, func, text) tuples are disambiguated by occurrence
    index in source order, so two copies of the same offending line in
    one function get two distinct baseline slots."""
    seen = {}
    for f in sorted(findings, key=Finding.sort_key):
        ident = (f.path, f.pass_id, f.func, f.text)
        n = seen.get(ident, 0)
        seen[ident] = n + 1
        blob = "%s::%s::%s::%s::%d" % (f.path, f.pass_id, f.func,
                                       f.text, n)
        f.fingerprint = hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]
    return findings


# ---------------------------------------------------------------------------
# pragma parsing:  # mxlint: allow(pass-id[, pass-id]) — reason
# ---------------------------------------------------------------------------

_PRAGMA_HEAD = "mxlint:"


def _parse_pragma_comment(comment):
    """``(allowed_ids, reason)`` from one comment string, or None when it
    carries no mxlint pragma. Grammar::

        # mxlint: allow(pass-id[, pass-id...])[ <sep> reason]

    where ``<sep>`` is em-dash / hyphen / colon (all optional)."""
    body = comment.lstrip("#").strip()
    if not body.startswith(_PRAGMA_HEAD):
        return None
    body = body[len(_PRAGMA_HEAD):].strip()
    if not body.startswith("allow(") or ")" not in body:
        return None
    inner, _, rest = body[len("allow("):].partition(")")
    ids = frozenset(p.strip() for p in inner.split(",") if p.strip())
    reason = rest.lstrip(" \t-—:–").strip()
    return ids, reason


class PragmaMap:
    """Line -> allowed pass ids for one module, with def/class-header
    pragmas expanded to the whole body and comment-only-line pragmas
    attached to the next code line.

    A pragma must carry a *reason* to suppress anything: ``allows``
    only honors entries whose reason text is non-empty. A bare
    ``# mxlint: allow(x)`` is inert — the finding survives, annotated
    so the author knows why (the old review-should-reject-bare-pragmas
    rule, made mechanical)."""

    def __init__(self, source, tree):
        per_line = {}      # lineno -> (ids, reason, comment_only)
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                parsed = _parse_pragma_comment(tok.string)
                if parsed is None:
                    continue
                line_text = source.splitlines()[tok.start[0] - 1]
                own = line_text.strip().startswith("#")
                per_line[tok.start[0]] = (parsed[0], parsed[1], own)
        except (tokenize.TokenError, IndentationError):
            pass
        self._line_allow = {}     # lineno -> {pass id -> reason}
        comment_only = []
        for lineno, (ids, reason, own) in per_line.items():
            if own:
                comment_only.append((lineno, ids, reason))
            else:
                slot = self._line_allow.setdefault(lineno, {})
                for pid in ids:
                    slot[pid] = reason
        # a comment-only pragma line blesses the next code line
        nlines = source.count("\n") + 1
        lines = source.splitlines()
        for lineno, ids, reason in comment_only:
            nxt = lineno + 1
            while nxt <= nlines and (nxt - 1 >= len(lines)
                                     or not lines[nxt - 1].strip()
                                     or lines[nxt - 1].strip()
                                     .startswith("#")):
                nxt += 1
            slot = self._line_allow.setdefault(nxt, {})
            for pid in ids:
                slot[pid] = reason
        # def/class-header pragmas cover the whole body
        self._ranges = []         # (start, end, {pass id -> reason})
        if tree is not None:
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                    continue
                header = node.lineno
                ids = self._line_allow.get(header)
                if ids:
                    self._ranges.append(
                        (header, node.end_lineno or header, ids))

    def entry(self, line, pass_id):
        """The pragma reason covering ``(line, pass_id)``, or None when
        no pragma names that pass there. An empty string means a bare
        (reasonless, therefore inert) pragma."""
        ids = self._line_allow.get(line)
        if ids:
            for pid in (pass_id, "*"):
                if pid in ids:
                    return ids[pid]
        for start, end, rids in self._ranges:
            if start <= line <= end:
                for pid in (pass_id, "*"):
                    if pid in rids:
                        return rids[pid]
        return None

    def allows(self, line, pass_id):
        return bool(self.entry(line, pass_id))


# ---------------------------------------------------------------------------
# per-module analysis context
# ---------------------------------------------------------------------------

class ModuleInfo:
    """Everything a pass needs about one file: source, lines, AST,
    pragma map, repo-relative path, and small shared lookups."""

    @staticmethod
    def _relpath_of(path, root):
        path = pathlib.Path(path)
        root = pathlib.Path(root)
        return str(path.relative_to(root)) \
            if root in path.parents or path == root else str(path)

    def __init__(self, path, root):
        self.path = pathlib.Path(path)
        self.relpath = self._relpath_of(self.path, root)
        self.source = self.path.read_text(encoding="utf-8",
                                          errors="replace")
        self.lines = self.source.splitlines()
        self.parse_error = None
        try:
            self.tree = ast.parse(self.source)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = e
        self.pragmas = PragmaMap(self.source, self.tree)
        self._parents = None
        self._qualnames = None

    # -- shared lookups ----------------------------------------------------
    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def numpy_aliases(self):
        """Local names bound to the numpy module by imports."""
        out = set()
        if self.tree is None:
            return out
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "numpy":
                        out.add(a.asname or "numpy")
        return out

    def parent_map(self):
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def qualname(self, node):
        """Dotted enclosing-scope name for a node (``Cls.meth`` /
        ``outer.<locals>.inner`` flattened to ``outer.inner``)."""
        if self.tree is None:
            return "<module>"
        if self._qualnames is None:
            self._qualnames = {}
            parents = self.parent_map()
            for n in ast.walk(self.tree):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    parts, cur = [n.name], parents.get(n)
                    while cur is not None:
                        if isinstance(cur, (ast.FunctionDef,
                                            ast.AsyncFunctionDef,
                                            ast.ClassDef)):
                            parts.append(cur.name)
                        cur = parents.get(cur)
                    self._qualnames[n] = ".".join(reversed(parts))
        parents = self.parent_map()
        cur = node
        while cur is not None:
            if cur in self._qualnames:
                return self._qualnames[cur]
            cur = parents.get(cur)
        return "<module>"

    def finding(self, node, pass_id, message):
        lineno = getattr(node, "lineno", 1)
        return Finding(self.relpath, lineno,
                       getattr(node, "col_offset", 0), pass_id, message,
                       text=self.line_text(lineno),
                       func=self.qualname(node))


# ---------------------------------------------------------------------------
# pass registry
# ---------------------------------------------------------------------------

class LintPass:
    """Base class for a pass plugin. Subclasses set ``name`` /
    ``description`` and implement either ``run(module) -> [Finding]``
    (``scope = "module"``) or ``run_project(project) -> [Finding]``
    (``scope = "project"``, whole-program passes); the framework
    applies pragmas, baseline and output handling either way."""

    name = None
    description = ""
    scope = "module"

    def run(self, module):
        raise NotImplementedError

    def run_project(self, project):
        raise NotImplementedError


_REGISTRY = {}


def register(cls):
    """Class decorator adding a pass to the registry (import a module
    defining registered passes and they become runnable — that is the
    whole plugin mechanism)."""
    assert cls.name, "a LintPass needs a name"
    _REGISTRY[cls.name] = cls
    return cls


def all_passes():
    # importing the package registers the built-in passes
    from . import passes  # noqa: F401
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def iter_py_files(paths):
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts:
                    continue
                yield f
        elif p.suffix == ".py":
            yield p


def _under_default_roots(path, root):
    try:
        rel = pathlib.Path(path).resolve().relative_to(
            pathlib.Path(root).resolve())
    except ValueError:
        return False
    from .project import DEFAULT_ROOT_DIRS
    return bool(rel.parts) and rel.parts[0] in DEFAULT_ROOT_DIRS


def build_project(paths, root, files=None):
    """The whole-program context for one lint invocation (see
    ``project.Project`` for the scope model): requested files select
    what is *reported*; the analyzed file set is the full default
    roots whenever the request lies inside them."""
    from .project import DEFAULT_ROOT_DIRS, Project
    root = pathlib.Path(root)
    report_files = [pathlib.Path(f) for f in (
        files if files is not None else iter_py_files(paths))]
    if report_files and all(_under_default_roots(f, root)
                            for f in report_files):
        project_files = [f for d in DEFAULT_ROOT_DIRS
                         for f in iter_py_files([root / d])]
        closed = True
    else:
        project_files = report_files
        closed = files is None and bool(paths) and \
            all(pathlib.Path(p).is_dir() for p in paths)
    modules, seen = [], set()
    for f in project_files:
        m = ModuleInfo(f, root)
        if m.relpath in seen:
            continue
        seen.add(m.relpath)
        modules.append(m)
    report_relpaths = {ModuleInfo._relpath_of(f, root)
                       for f in report_files}
    return Project(modules, root=root, closed=closed,
                   report_relpaths=report_relpaths)


def run_paths(paths, root=None, pass_names=None, files=None):
    """Run the selected passes over every .py under ``paths`` (or the
    explicit ``files`` list); returns pragma-filtered, fingerprinted,
    sorted findings. Module-scope passes run per reported file;
    project-scope passes run once over the whole-program context and
    are filtered down to findings anchored in reported files (or in a
    contract doc like ``docs/env_vars.md``)."""
    root = pathlib.Path(root) if root is not None \
        else pathlib.Path.cwd()
    registry = all_passes()
    if pass_names:
        unknown = set(pass_names) - set(registry)
        if unknown:
            raise SystemExit("mxlint: unknown pass(es): %s (have: %s)"
                             % (", ".join(sorted(unknown)),
                                ", ".join(sorted(registry))))
        registry = {k: v for k, v in registry.items() if k in pass_names}
    instances = [cls() for _, cls in sorted(registry.items())]
    project = build_project(paths, root, files=files)
    findings = []
    for relpath in sorted(project.report_relpaths):
        module = project.modules.get(relpath)
        if module is None:
            continue
        if module.parse_error is not None:
            findings.append(Finding(
                module.relpath, module.parse_error.lineno or 1, 0,
                "parse", "syntax error: %s" % module.parse_error.msg))
            continue
        for p in instances:
            if p.scope != "module":
                continue
            for f in p.run(module):
                if _apply_pragma(module, f):
                    findings.append(f)
    for p in instances:
        if p.scope != "project":
            continue
        for f in p.run_project(project):
            owner = project.modules.get(f.path)
            if owner is not None and f.path not in \
                    project.report_relpaths:
                continue       # anchored in an unchanged project file
            if owner is not None and not _apply_pragma(owner, f):
                continue
            findings.append(f)
    return assign_fingerprints(sorted(findings, key=Finding.sort_key))


def _apply_pragma(module, finding):
    """True when the finding should be REPORTED. A pragma with a
    reason suppresses it; a bare pragma is inert but annotates the
    surviving finding (the reason requirement is mechanical, not a
    review convention)."""
    entry = module.pragmas.entry(finding.line, finding.pass_id)
    if entry:
        return False
    if entry == "":
        finding.message += (" [a pragma names this pass here but "
                            "carries no reason — add `— <why>` to "
                            "bless it]")
    return True


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path):
    path = pathlib.Path(path)
    if not path.exists():
        return {"version": 1, "findings": []}
    with open(path) as f:
        return json.load(f)


def save_baseline(path, findings):
    doc = {"version": 1,
           "comment": "mxlint grandfathered findings; regenerate with "
                      "`python tools/mxlint.py <paths> --write-baseline`"
                      " (see docs/static_analysis.md)",
           "findings": [f.to_dict() for f in findings]}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def diff_against_baseline(findings, baseline):
    """``(new, grandfathered, stale)``: findings not in the baseline,
    findings matched by it, and baseline entries no longer observed
    (fixed or drifted — candidates for pruning)."""
    base = {e["fingerprint"]: e for e in baseline.get("findings", [])}
    new = [f for f in findings if f.fingerprint not in base]
    old = [f for f in findings if f.fingerprint in base]
    seen = {f.fingerprint for f in findings}
    stale = [e for e in base.values() if e["fingerprint"] not in seen]
    return new, old, stale
