"""trace-purity pass: host syncs and impure state writes inside traced
(jit-compiled) functions.

A traced function runs once at trace time and is then replayed by XLA:
any host sync inside it (``asnumpy()``, ``.item()``, ``float()`` /
``int()`` of a traced value, ``np.asarray``, ``jax.device_get``,
``block_until_ready``) forces a device round-trip per call during
tracing — or, worse, silently re-introduces a per-batch sync when the
value is an operand — and any write to ``self.X`` / nonlocal state runs
ONCE at trace time and never again, which is almost never what the
author meant. This is exactly the regression class PR 5 removed from
the Module hot loop (`zero per-batch host syncs`), so it must not come
back by accident.

Roots: a function is *traced* when it is

* decorated with ``jax.jit`` / ``jit`` /
  ``functools.partial(jax.jit, ...)``, or
* passed as the first argument to a ``jax.jit(...)`` / ``jit(...)``
  call anywhere in the module (``jitted = jax.jit(train_step, ...)``),
  or
* listed in :data:`EXTRA_ROOTS` — the fused-step helpers that only ever
  execute inside a traced program (``functional_optimizer_step``: every
  call site sits inside a jitted train step).

Reachability is closed over same-module calls (plain names, nested
defs, ``self.`` methods of the enclosing class) — the fused step's
``fused -> _forward -> eval_graph``-style chains are covered as far as
this module defines them; cross-module callees are out of scope by
design (each module is analyzed with its own roots).

Checks are syntactic, not dataflow: ``float(x)`` on a trace-time Python
constant is flagged too. That is deliberate — inside a jitted function
"host value" vs "traced value" is precisely the distinction authors get
wrong, and the blessing for a reviewed constant is a
``# mxlint: allow(trace-purity) — <why this is trace-time>`` pragma.
"""
from __future__ import annotations

import ast

from ..core import LintPass, register

# (module path suffix, function bare name) roots that are only ever
# called from inside traced programs
EXTRA_ROOTS = (
    ("mxtpu/optimizer.py", "functional_optimizer_step"),
)

_HOST_ATTR_CALLS = frozenset(("asnumpy", "item", "tolist",
                              "block_until_ready"))
_HOST_NP_FUNCS = frozenset(("asarray", "array", "copy", "frombuffer",
                            "save", "load"))


def _is_jit_expr(node):
    """True for ``jax.jit`` / ``jit`` name expressions."""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_call_targets(call):
    """Candidate traced-function names for a ``jax.jit(X, ...)`` call:
    ``X`` itself when it is a bare name; the functions a lambda ``X``
    calls (``jax.jit(lambda *a: wrapped(*a))``); and the name arguments
    of a wrapper call ``X`` (``jax.jit(maybe_remat(body, ...))`` /
    ``jax.jit(pl.pallas_call(kernel, ...))``) — one unwrap level."""
    if not _is_jit_expr(call.func) or not call.args:
        return ()
    target = call.args[0]
    if isinstance(target, ast.Name):
        return (target.id,)
    out = []
    if isinstance(target, ast.Lambda):
        for node in ast.walk(target.body):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name):
                out.append(node.func.id)
    elif isinstance(target, ast.Call):
        for a in target.args:
            if isinstance(a, ast.Name):
                out.append(a.id)
    return tuple(out)


def _decorated_as_jit(func):
    for dec in func.decorator_list:
        if _is_jit_expr(dec):
            return True
        if isinstance(dec, ast.Call):
            # functools.partial(jax.jit, ...) or jax.jit(...) factory
            if _is_jit_expr(dec.func):
                return True
            fname = dec.func.attr if isinstance(dec.func, ast.Attribute) \
                else (dec.func.id if isinstance(dec.func, ast.Name)
                      else None)
            if fname == "partial" and dec.args and \
                    _is_jit_expr(dec.args[0]):
                return True
    return False


@register
class TracePurityPass(LintPass):
    name = "trace-purity"
    description = ("host syncs / impure state writes inside functions "
                   "reachable from a jax.jit root")

    def run(self, module):
        tree = module.tree
        funcs = {}          # bare name -> [FunctionDef]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, []).append(node)
        roots = set()
        for name, defs in funcs.items():
            for d in defs:
                if _decorated_as_jit(d):
                    roots.add(d)
        # wrapper aliases: `wrapped = maybe_remat(body, ...)` makes a
        # jit of `wrapped` a jit of `body` (one unwrap level)
        aliases = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                inner = [a.id for a in node.value.args
                         if isinstance(a, ast.Name) and a.id in funcs]
                if inner:
                    aliases[node.targets[0].id] = inner
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                for target in _jit_call_targets(node):
                    for name in [target] + aliases.get(target, []):
                        if name in funcs:
                            roots.update(funcs[name])
        for suffix, fname in EXTRA_ROOTS:
            if module.relpath.endswith(suffix) and fname in funcs:
                roots.update(funcs[fname])
        if not roots:
            return []
        reachable = self._close_over_calls(module, funcs, roots)
        np_aliases = module.numpy_aliases()
        out = []
        for fn in sorted(reachable, key=lambda n: n.lineno):
            out.extend(self._check_traced(module, fn, np_aliases))
        return out

    # -- reachability ------------------------------------------------------
    @staticmethod
    def _close_over_calls(module, funcs, roots):
        reachable = set(roots)
        work = list(roots)
        while work:
            fn = work.pop()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = None
                if isinstance(f, ast.Name):
                    name = f.id
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self":
                    name = f.attr
                if name and name in funcs:
                    for cand in funcs[name]:
                        if cand not in reachable:
                            reachable.add(cand)
                            work.append(cand)
        return reachable

    # -- the checks --------------------------------------------------------
    def _check_traced(self, module, fn, np_aliases):
        out = []
        ctx = fn.name
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                out.extend(self._check_call(module, node, np_aliases,
                                            ctx))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        out.append(module.finding(
                            node, self.name,
                            "write to %s inside traced %s() runs once "
                            "at trace time, not per step"
                            % (ast.unparse(t), ctx)))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                out.append(module.finding(
                    node, self.name,
                    "%s write inside traced %s() is a trace-time "
                    "side effect" % (type(node).__name__.lower(), ctx)))
        return out

    def _check_call(self, module, node, np_aliases, ctx):
        f = node.func
        out = []
        if isinstance(f, ast.Attribute):
            if f.attr in _HOST_ATTR_CALLS:
                out.append(module.finding(
                    node, self.name,
                    ".%s() inside traced %s() is a host sync"
                    % (f.attr, ctx)))
            elif f.attr == "device_get":
                out.append(module.finding(
                    node, self.name,
                    "device_get inside traced %s() is a host sync"
                    % ctx))
            elif isinstance(f.value, ast.Name) and \
                    f.value.id in np_aliases and \
                    f.attr in _HOST_NP_FUNCS:
                out.append(module.finding(
                    node, self.name,
                    "%s.%s() inside traced %s() materializes on host "
                    "(use jnp, or hoist out of the traced function)"
                    % (f.value.id, f.attr, ctx)))
        elif isinstance(f, ast.Name):
            if f.id in ("float", "int") and node.args and \
                    not isinstance(node.args[0], ast.Constant):
                out.append(module.finding(
                    node, self.name,
                    "%s() of a non-literal inside traced %s() forces a "
                    "host sync if the value is traced" % (f.id, ctx)))
            elif f.id == "print":
                out.append(module.finding(
                    node, self.name,
                    "print() inside traced %s() fires at trace time "
                    "only (use jax.debug.print)" % ctx))
        return out
