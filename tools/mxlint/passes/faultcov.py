"""fault-coverage pass: every fault-injection point is a tested,
documented contract.

``mxtpu/fault.py`` fires deterministic injection points
(``fire("server.recv", ...)``) that the fault-matrix tests and the
``MXTPU_FAULT_SPEC`` grammar in ``docs/env_vars.md`` target by name.
A point added in code but absent from the grammar is un-targetable by
operators; one absent from the fault matrix is an untested recovery
path — both are exactly the drift this pass pins:

* every ``fire("<point>")`` literal in the analyzed tree must appear
  in the ``point=...`` alternation of the fault grammar
  (``docs/env_vars.md``, resolved by walk-up so a fixture corpus can
  carry its own copy);
* in closed/whole-tree runs, every fire point must additionally appear
  in at least one fault-matrix test row (textual ``point=<name>`` or
  bare ``"<name>"`` in the sibling ``tests/`` corpus).

Findings anchor at the ``fire(...)`` call site, so a deliberately
untestable point carries its pragma next to the code it excuses.
"""
from __future__ import annotations

import ast
import re

from ..core import LintPass, register

_POINT = re.compile(r"^[a-z_]+(\.[a-z_]+)+$")
# the grammar row: point=worker.send\|worker.recv\|... (the backslashes
# are markdown table escapes)
_GRAMMAR = re.compile(r"point=((?:[a-z_.]+\\?\|)*[a-z_.]+)")


def _grammar_points(doc_text):
    out = set()
    for m in _GRAMMAR.finditer(doc_text):
        for p in m.group(1).replace("\\|", "|").split("|"):
            if _POINT.match(p):
                out.add(p)
    return out


@register
class FaultCoveragePass(LintPass):
    name = "fault-coverage"
    scope = "project"
    description = ("fire(<point>) literals missing from the "
                   "MXTPU_FAULT_SPEC grammar or the fault-matrix "
                   "tests")

    def run_project(self, project):
        sites = []               # (point, relpath, lineno)
        for relpath, module in sorted(project.modules.items()):
            if module.tree is None:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if name != "fire" or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        _POINT.match(arg.value):
                    sites.append((arg.value, relpath, node.lineno))
        if not sites:
            return []
        doc = project.find_contract_file("docs", "env_vars.md")
        grammar = _grammar_points(
            doc.read_text(encoding="utf-8", errors="replace")) \
            if doc is not None else None
        tests = project.test_corpus() if project.closed else None
        out = []
        for point, relpath, lineno in sites:
            module = project.modules[relpath]
            if grammar is not None and point not in grammar:
                out.append(module.finding(
                    _Line(lineno), self.name,
                    "fault point %r is not in the MXTPU_FAULT_SPEC "
                    "grammar (%s) — operators cannot target it"
                    % (point, _rel(doc, project))))
            if tests:
                needle_a = "point=%s" % point
                if not any(needle_a in text or ('"%s"' % point) in text
                           or ("'%s'" % point) in text
                           for text in tests.values()):
                    out.append(module.finding(
                        _Line(lineno), self.name,
                        "fault point %r appears in no fault-matrix "
                        "test row — its recovery path is untested"
                        % point))
        return out


def _rel(path, project):
    try:
        return str(path.relative_to(project.root))
    except ValueError:
        return str(path)


class _Line:
    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0
