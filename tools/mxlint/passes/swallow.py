"""except-swallow pass: AST-accurate ``except: pass`` detection, scoped
by module criticality.

A handler whose body is only ``pass``/``...`` turns a failure into
silence. On the kvstore/engine/fault/checkpoint/io paths that silence is
a hung or silently-corrupt fleet, so there ANY broad swallow
(``except:``, ``except Exception:``, ``except BaseException:``, or a
tuple containing one of those) is a finding. Elsewhere only the bare /
``BaseException`` forms are flagged — a narrow ``except ValueError:
pass`` is a normal idiom, and a broad one in cold code is grandfathered
by the baseline rather than blocking CI.

Unlike the old regex (which matched the *next line* only), the AST form
sees the handler body whatever its layout, and a swallow annotated
``# mxlint: allow(except-swallow) — reason`` on the ``except`` line is
deliberately blessed.
"""
from __future__ import annotations

import ast
import fnmatch

from ..core import LintPass, register

# module paths where a swallowed error means a hung or corrupt fleet;
# matched against the repo-relative path with fnmatch
CRITICAL = (
    "*mxtpu/kvstore.py", "*mxtpu/kvstore_async.py",
    "*mxtpu/kvstore_server.py", "*mxtpu/engine.py", "*mxtpu/fault.py",
    "*mxtpu/checkpoint.py", "*mxtpu/resilience.py", "*mxtpu/io.py",
    "*mxtpu/image.py", "*mxtpu/executor.py", "*mxtpu/module/*",
    "*mxtpu/parallel/*", "*tools/launch.py",
)

_BROAD = frozenset(("Exception", "BaseException"))


def _exc_names(handler):
    t = handler.type
    if t is None:
        return {None}
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    names = set()
    for e in elts:
        if isinstance(e, ast.Name):
            names.add(e.id)
        elif isinstance(e, ast.Attribute):
            names.add(e.attr)
        else:
            names.add("?")
    return names


def _body_is_swallow(handler):
    return all(isinstance(s, ast.Pass)
               or (isinstance(s, ast.Expr)
                   and isinstance(s.value, ast.Constant)
                   and s.value.value is Ellipsis)
               for s in handler.body)


@register
class ExceptSwallowPass(LintPass):
    name = "except-swallow"
    description = "except-with-pass-only handlers, scoped by criticality"

    def run(self, module):
        critical = any(fnmatch.fnmatch(module.relpath, pat)
                       for pat in CRITICAL)
        out = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _body_is_swallow(node):
                continue
            names = _exc_names(node)
            bare = None in names or "BaseException" in names
            broad = bare or (names & _BROAD)
            if bare or (critical and broad):
                what = "bare except" if None in names else \
                    "except %s" % "/".join(sorted(n for n in names if n))
                out.append(module.finding(
                    node, self.name,
                    "%s: pass swallows failures silently%s" %
                    (what, " on a critical fleet path" if critical
                     else "")))
        return out
