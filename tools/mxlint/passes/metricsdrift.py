"""metrics-drift pass: the metric catalog and the code must describe
the same set (the ``env-drift`` pattern applied to the observability
plane, ISSUE 14).

A *registration site* is a call ``<recv>.counter("a.b.c", ...)`` /
``.gauge`` / ``.histogram`` / ``.view`` whose first argument is a
string literal shaped like a dotted metric name (``seg.seg[...]``,
lowercase) — the only way instruments enter :mod:`mxtpu.obs.metrics`'
registry. A *definition row* is a markdown table line in
``docs/observability.md`` whose first cell carries the name in
backticks. Two drift directions:

* a metric registered in code with no definition row — finding at the
  registration site (code-anchored, runs in every mode): an
  undocumented metric is invisible to operators and to the
  ROADMAP-3 controller's contract;
* in closed/whole-tree runs, a definition row whose metric has no
  registration site — finding anchored at the doc line: a stale
  catalog row describes telemetry that no longer exists. Retired
  metrics stay honest with a literal ``(removed)`` marker.
"""
from __future__ import annotations

import ast
import re

from ..core import Finding, LintPass, register

_METHODS = ("counter", "gauge", "histogram", "view")
_NAME = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
# a definition row: first table cell contains a backticked dotted name
_DEF_ROW = re.compile(r"^\|[^|]*`[a-z0-9_]+(\.[a-z0-9_]+)+`")
_CELL_NAME = re.compile(r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`")
_REMOVED = re.compile(r"\(removed[):\s]", re.IGNORECASE)


class _DocIndex:
    def __init__(self, path, project):
        self.path = path
        try:
            self.relpath = str(path.relative_to(project.root))
        except ValueError:
            self.relpath = str(path)
        self.defined = {}        # metric -> first definition line
        self.removed = set()
        for lineno, line in enumerate(
                path.read_text(encoding="utf-8",
                               errors="replace").splitlines(), 1):
            if not _DEF_ROW.match(line):
                continue
            first_cell = line.split("|")[1] if "|" in line else line
            for m in _CELL_NAME.findall(first_cell):
                self.defined.setdefault(m, lineno)
                if _REMOVED.search(line):
                    self.removed.add(m)


def _reg_name(call):
    """The literal metric name of a registration call, else None."""
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _METHODS:
        return None
    if not call.args:
        return None
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str) \
            and _NAME.match(a.value):
        return a.value
    return None


@register
class MetricsDriftPass(LintPass):
    name = "metrics-drift"
    scope = "project"
    description = ("metric registration sites vs docs/observability.md:"
                   " undocumented metrics and documented-but-dead rows")

    def run_project(self, project):
        sites = {}               # name -> [(relpath, lineno)]
        for relpath, module in sorted(project.modules.items()):
            if module.tree is None:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _reg_name(node)
                if name is not None:
                    sites.setdefault(name, []).append(
                        (relpath, node.lineno))
        if not sites:
            return []
        doc_path = project.find_contract_file("docs",
                                              "observability.md")
        doc = _DocIndex(doc_path, project) if doc_path is not None \
            else None
        out = []
        if doc is None:
            return out
        for name, where in sorted(sites.items()):
            if name in doc.defined:
                continue
            for relpath, lineno in where:
                out.append(project.modules[relpath].finding(
                    _Line(lineno), self.name,
                    "metric %s is registered here but has no "
                    "definition row in %s" % (name, doc.relpath)))
        if project.contract_is_closed(doc_path):
            for name, lineno in sorted(doc.defined.items()):
                if name in sites or name in doc.removed:
                    continue
                out.append(Finding(
                    doc.relpath, lineno, 0, self.name,
                    "metric %s is documented but nothing registers "
                    "it — delete the row or mark it (removed)" % name,
                    text="", func="<doc>"))
        return out


class _Line:
    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0
