"""Built-in mxlint passes. Importing this package registers them; a new
pass is one module defining a ``@register``-ed ``LintPass`` subclass
plus an import line here (docs/static_analysis.md, "Adding a pass")."""
from __future__ import annotations

from . import blocking    # noqa: F401
from . import blockinglock  # noqa: F401
from . import donation    # noqa: F401
from . import envdrift    # noqa: F401
from . import faultcov    # noqa: F401
from . import locks       # noqa: F401
from . import metricsdrift  # noqa: F401
from . import races       # noqa: F401
from . import resource    # noqa: F401
from . import swallow     # noqa: F401
from . import tracepurity  # noqa: F401
from . import wireproto   # noqa: F401
