"""blocking-call pass: unbounded blocking calls detected on the call AST.

Subsumes the socket and sync-wait regex rules of the old
``ci/check_robustness.py`` (its 3-line window missed wrapped calls; the
AST node anchors the finding at the call regardless of layout):

* ``.recv(`` / ``.recv_into(`` — raw socket reads must go through an
  audited deadline-carrying loop (``_recv_exact``), never appear inline.
* ``settimeout(None)`` — turning a socket's deadline off.
* ``create_connection(...)`` with no ``timeout`` (positional or
  keyword) — connect can hang on a black-holed host forever.
* ``.wait()`` / ``.join()`` / ``.get()`` with **no positional argument
  and no ``timeout=``** — the bare forms of Event/Condition/Thread/
  queue/future waits, exactly how a dead peer hangs a survivor.
  ``dict.get(key)`` and friends carry a positional argument and are
  never matched (the old regex needed an ALLOW pin for each of those).

Deliberate block-forever points (a server role's ``join()``, the shared
frame-read loop) carry ``# mxlint: allow(blocking-call) — reason``.
"""
from __future__ import annotations

import ast

from ..core import LintPass, register

_WAIT_NAMES = frozenset(("wait", "join", "get"))


def _has_timeout(call):
    return any(kw.arg == "timeout" for kw in call.keywords)


@register
class BlockingCallPass(LintPass):
    name = "blocking-call"
    description = ("unbounded recv/wait/get/join/create_connection/"
                   "settimeout(None) calls")

    def run(self, module):
        out = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            attr = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None)
            if attr is None:
                continue
            if attr in ("recv", "recv_into") and \
                    isinstance(func, ast.Attribute):
                out.append(module.finding(
                    node, self.name,
                    "raw .%s() read — socket reads must go through the "
                    "deadline-carrying frame loop" % attr))
            elif attr == "settimeout" and len(node.args) == 1 and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value is None:
                out.append(module.finding(
                    node, self.name,
                    "settimeout(None) disables the socket deadline"))
            elif attr == "create_connection":
                if len(node.args) < 2 and not _has_timeout(node):
                    out.append(module.finding(
                        node, self.name,
                        "create_connection() without an explicit "
                        "timeout can hang on connect forever"))
            elif attr in _WAIT_NAMES and \
                    isinstance(func, ast.Attribute):
                if not node.args and not _has_timeout(node):
                    out.append(module.finding(
                        node, self.name,
                        ".%s() with no timeout — a dead peer hangs "
                        "this caller forever" % attr))
        return out
