"""use-after-donate pass: reads of a buffer after it was passed at a
donated argument position.

The fused train step's contract (PR 5, ``mxtpu/module/fused.py``): a
call to a program jitted with ``donate_argnums`` invalidates the
caller's input buffers at the donated positions — every wrapper must be
rebound (``nd._data = new_value``) before anyone reads it again. A read
of the *donated local* after the call is at best a stale value and at
worst a runtime "array has been deleted" error that only fires on real
hardware, where donation actually aliases.

Detection (intra-function, linear over the statement list — branches
are walked in source order, which over-approximates; a pragma blesses
the reviewed counterexample):

1. **Donating callables.** A local name is donating when it is bound
   (possibly through one tuple-unpack) from

   * ``jax.jit(f, ..., donate_argnums=SPEC)`` — SPEC read from the
     literal tuple/int, or from a prior ``SPEC = (...)`` assignment
     (the ``X if cond else ()`` pattern takes the donating arm:
     conservative), or
   * a factory listed in :data:`DONATING_FACTORIES` — e.g.
     ``make_fused_train_step`` returns ``(fn, other_names)`` where
     ``fn`` donates positions (0, 1, 2, 4, 5, 7); the spec lives here
     so the linter knows the executor's contract without dataflow
     across modules.

2. **Kill set.** At a call ``fn(a0, a1, ...)`` of a donating name, the
   arguments at donated positions that are plain names or dotted
   attribute paths become *dead*.

3. **Verdict.** A later load of a dead path in the same function is a
   finding; a store to the exact path (the ``_data`` rebind pattern
   rebinds the wrapper, and reassigning the local itself) revives it.
"""
from __future__ import annotations

import ast

from ..core import LintPass, register

# factory bare name -> (index of the donating fn in the returned tuple
#                        or None when returned directly, donated args)
DONATING_FACTORIES = {
    "make_fused_train_step": (0, (0, 1, 2, 4, 5, 7)),
    # the grad-emitting dist mode: params are read-only, aux/key/metric
    # accumulator donated (executor.make_fused_grad_step)
    "make_fused_grad_step": (0, (1, 3, 4)),
    # the dist_local apply half: params/state/step-count donated,
    # pulled grads and lr are not (executor.make_fused_apply_step,
    # returned directly — not in a tuple)
    "make_fused_apply_step": (None, (0, 1, 3)),
}


def _dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _donate_spec(call, const_env):
    """The donate_argnums tuple of a jax.jit(...) call, or None."""
    f = call.func
    is_jit = (isinstance(f, ast.Attribute) and f.attr == "jit") or \
        (isinstance(f, ast.Name) and f.id == "jit")
    if not is_jit:
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        return _spec_value(kw.value, const_env)
    return None


def _spec_value(node, const_env):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    if isinstance(node, ast.IfExp):
        # `(0, 1, 2) if self._donate else ()` — analyze the donating arm
        for arm in (node.body, node.orelse):
            spec = _spec_value(arm, const_env)
            if spec:
                return spec
        return None
    if isinstance(node, ast.Name):
        return const_env.get(node.id)
    return None


def _flatten(body):
    """Statements of a function in source order, recursing into every
    compound block (linear over-approximation of control flow)."""
    for stmt in body:
        yield stmt
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub and not isinstance(stmt, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.ClassDef)):
                yield from _flatten(sub)
        for h in getattr(stmt, "handlers", []) or []:
            yield from _flatten(h.body)


@register
class DonationPass(LintPass):
    name = "use-after-donate"
    description = ("reads of an array after it was passed at a donated "
                   "argument position")

    def run(self, module):
        out = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_function(module, node))
        return out

    def _check_function(self, module, fn):
        stmts = [s for s in _flatten(fn.body)
                 if not isinstance(s, (ast.FunctionDef,
                                       ast.AsyncFunctionDef,
                                       ast.ClassDef))]
        const_env = {}          # name -> literal int tuple
        donating = {}           # local name -> donated positions
        tainted = {}            # name tainted by a factory ->
        #                         (tuple_index, spec)
        dead = {}               # dotted path -> (call lineno, fn name)
        findings = []

        for stmt in stmts:
            # 1. findings first: loads of dead paths in this statement
            #    (before this statement's own stores revive anything)
            if dead:
                findings.extend(
                    self._dead_loads(module, stmt, dead))
            # 2. donating calls anywhere in the statement kill their
            #    donated arguments (the call runs before the
            #    statement's own stores, so `params, _ = fn(params)`
            #    kills and then revives — the rebind idiom stays clean)
            for call in self._calls_of(stmt):
                spec = self._call_spec(call, donating, const_env)
                if spec is None:
                    continue
                callee = _dotted(call.func) or "<fn>"
                for pos in spec:
                    if pos < len(call.args):
                        path = _dotted(call.args[pos])
                        if path:
                            dead[path] = (call.lineno, callee)
            # 3. track assignments; stores revive their exact paths
            if isinstance(stmt, ast.Assign):
                self._track_assign(stmt, const_env, donating, tainted)
                for t in stmt.targets:
                    self._revive(t, dead)
            elif isinstance(stmt, ast.AugAssign):
                self._revive(stmt.target, dead)
        return findings

    # -- bookkeeping -------------------------------------------------------
    @staticmethod
    def _calls_of(stmt):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                yield node

    def _track_assign(self, stmt, const_env, donating, tainted):
        value = stmt.value
        targets = stmt.targets
        # literal int tuples feed donate_argnums resolution
        if len(targets) == 1 and isinstance(targets[0], ast.Name):
            spec = _spec_value(value, const_env) \
                if not isinstance(value, ast.Call) else None
            if spec is not None:
                const_env[targets[0].id] = spec
        if isinstance(value, ast.Call):
            spec = _donate_spec(value, const_env)
            fname = value.func.attr \
                if isinstance(value.func, ast.Attribute) else (
                    value.func.id if isinstance(value.func, ast.Name)
                    else None)
            if spec is not None:
                for t in targets:
                    if isinstance(t, ast.Name):
                        donating[t.id] = spec
            elif fname in DONATING_FACTORIES:
                idx, fspec = DONATING_FACTORIES[fname]
                for t in targets:
                    if isinstance(t, ast.Name):
                        if idx is None:
                            donating[t.id] = fspec
                        else:
                            tainted[t.id] = (idx, fspec)
                    elif isinstance(t, ast.Tuple) and idx is not None \
                            and idx < len(t.elts) and \
                            isinstance(t.elts[idx], ast.Name):
                        donating[t.elts[idx].id] = fspec
        elif isinstance(value, ast.Name) and value.id in tainted:
            for t in targets:
                if isinstance(t, ast.Name):
                    tainted[t.id] = tainted[value.id]
                elif isinstance(t, ast.Tuple):
                    idx, fspec = tainted[value.id]
                    if idx < len(t.elts) and \
                            isinstance(t.elts[idx], ast.Name):
                        donating[t.elts[idx].id] = fspec
        elif isinstance(value, ast.Subscript) and \
                isinstance(value.value, ast.Name) and \
                value.value.id in tainted and \
                isinstance(value.slice, ast.Constant):
            idx, fspec = tainted[value.value.id]
            if value.slice.value == idx:
                for t in targets:
                    if isinstance(t, ast.Name):
                        donating[t.id] = fspec

    def _call_spec(self, call, donating, const_env):
        name = _dotted(call.func)
        if name in donating:
            return donating[name]
        # direct jax.jit(f, donate_argnums=...)(args) immediate call
        if isinstance(call.func, ast.Call):
            return _donate_spec(call.func, const_env)
        return None

    @staticmethod
    def _revive(target, dead):
        path = _dotted(target)
        if path is None:
            if isinstance(target, ast.Tuple):
                for e in target.elts:
                    DonationPass._revive(e, dead)
            return
        dead.pop(path, None)
        # rebinding a wrapper's attribute revives the wrapper path too
        # (nd._data = new  revives nd._data, not nd itself: reading the
        # NDArray wrapper was always fine — only raw handles die)

    def _dead_loads(self, module, stmt, dead):
        out = []
        # stores in this very statement must not count as loads
        store_paths = set()
        if isinstance(stmt, (ast.Assign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                p = _dotted(t)
                if p:
                    store_paths.add(p)
        for node in ast.walk(stmt):
            path = _dotted(node) if isinstance(node,
                                               (ast.Name,
                                                ast.Attribute)) else None
            if path is None or path not in dead or \
                    path in store_paths:
                continue
            if isinstance(getattr(node, "ctx", None), ast.Store):
                continue
            # attribute chains walk their sub-chains too; report the
            # exact dead path once per statement
            lineno, callee = dead[path]
            out.append(module.finding(
                node, self.name,
                "%r is read after being donated to %s() at line %d — "
                "the buffer is invalidated; rebind before reading"
                % (path, callee, lineno)))
            store_paths.add(path)   # one finding per statement per path
        return out
