"""shared-state-race pass: Eraser-style lockset race detection over the
whole program.

For every class attribute reachable from >= 2 concurrency roots (the
``Thread(target=)``/``submit`` entry points the project indexes, RPC
dispatch handlers — the local transport runs those on the requesting
thread — and the main-thread public surface) with at least one
non-init write, the pass intersects the *effective lockset* (locks
held per statement, plus the one-level caller context: locks held at
every call site of a same-class helper) across all live access sites.
An empty intersection means no single lock consistently guards the
attribute: a candidate data race, reported at every function that
touches it — so a race split across a sender thread and the training
thread is caught from both modules.

Exemptions (the lockset core applies them; docs/static_analysis.md
"Lockset model" documents the reasoning):

* init-phase writes — ``__init__`` assignments lexically before the
  first thread-spawn point construct the object before it escapes;
* lock-named guard attributes (``*lock*``/``*cv``/``*cond*``...) —
  they ARE the synchronization;
* attributes bound to internally-synchronized objects (Queue, Event,
  deque, threading.local, executors) and to obs metrics-plane
  instruments (``counter(...)``/``.labels(...)`` series carry
  per-series locks — the registry models them, see obs/metrics.py).

A deliberate lock-free idiom (a GIL-atomic flag read on a hot path, a
monotone watermark) carries ``# mxlint: allow(shared-state-race) —
<why the unlocked access is safe>``; the reason is mandatory — a
bare pragma does not suppress.
"""
from __future__ import annotations

from ..core import LintPass, register
from ..locksets import lockset_model


def _fmt_locks(tokens):
    return "{%s}" % ", ".join(sorted(tokens)) if tokens else "no lock"


@register
class SharedStateRacePass(LintPass):
    name = "shared-state-race"
    scope = "project"
    description = ("class attribute shared across concurrency roots "
                   "with >=1 write and an empty site-lockset "
                   "intersection (candidate data race)")

    def run_project(self, project):
        model = lockset_model(project)
        out = []
        for (attr_key, sites, contexts, offending) in model.races():
            (_rel, cls), attr = attr_key
            # one finding per offending function, anchored at its first
            # offending write site (else read), so both sides of a
            # cross-module race surface and can be pragma'd per site —
            # a correctly-locked reader of the same attribute stays
            # quiet
            by_func = {}
            for s in offending:
                by_func.setdefault(s.func_key, []).append(s)
            nwrites = sum(1 for s in sites if s.write)
            for func_key, fsites in sorted(by_func.items()):
                fsites.sort(key=lambda s: (not s.write, s.lineno))
                anchor = fsites[0]
                module = project.modules.get(anchor.relpath)
                if module is None:
                    continue
                rw = "writes" if anchor.write else "reads"
                eff = model.effective(anchor)
                f = module.finding(
                    _Anchor(anchor.lineno), self.name,
                    "unlocked shared state %s.%s: this function %s it "
                    "under %s, but no lock is common to all %d sites "
                    "(%d writes) across %d concurrency roots"
                    % (cls, attr, rw, _fmt_locks(eff), len(sites),
                       nwrites, len(contexts)))
                f.func = anchor.func_key[1]
                out.append(f)
        return out


class _Anchor:
    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0
