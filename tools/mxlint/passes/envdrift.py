"""env-drift pass: ``MXTPU_*`` knobs and ``docs/env_vars.md`` must
describe the same set.

Read-site extraction is whole-program and AST-accurate, which is what
the old grep audit could never be:

* direct reads — ``os.environ.get("MXTPU_X", ...)`` (wrapped over any
  number of lines), ``os.environ["MXTPU_X"]``, ``os.getenv``,
  ``environ.setdefault``, and ``"MXTPU_X" in os.environ`` membership
  probes;
* helper reads — a project function whose parameter flows into one of
  the direct forms (``_env_int(name, default)``) is an *env-read
  wrapper*; every resolvable call to it with a literal key is a read
  site. Resolution goes through the project symbol table, so the
  wrapper and its callers may live in different modules.

Documentation is a definition row in ``env_vars.md``: a markdown table
line whose first cell names the variable in backticks. Two drift
directions:

* a read site whose variable has no definition row — finding at the
  read site (code-anchored, runs in every mode);
* in closed/whole-tree runs, a definition row whose variable has no
  read site in the project or the sibling ``tests/`` corpus — finding
  anchored at the doc line. Rows describing retired knobs stay
  honest with a literal ``(removed)`` marker instead of deletion-by-
  forgetting.
"""
from __future__ import annotations

import ast
import re

from ..core import Finding, LintPass, register
from ..project import env_reads_in_text

_VAR = re.compile(r"MXTPU_[A-Z0-9_]+")
# a definition row: first table cell contains `MXTPU_...` (possibly
# several, e.g. "| `MXTPU_PS_BACKOFF` / `MXTPU_PS_BACKOFF_MAX` | ...")
_DEF_ROW = re.compile(r"^\|[^|]*`[^`|]*MXTPU_")
_REMOVED = re.compile(r"\(removed[):\s]", re.IGNORECASE)


def _environ_expr(node):
    """True for ``os.environ`` / ``environ`` / ``os.environ.copy()``-
    rooted bases that denote the process environment."""
    if isinstance(node, ast.Attribute):
        return node.attr == "environ"
    if isinstance(node, ast.Name):
        return node.id == "environ"
    return False


def _key_node(call):
    """The key-argument node of a direct environ read call, or None."""
    f = call.func
    if not isinstance(f, (ast.Attribute, ast.Name)):
        return None
    name = f.attr if isinstance(f, ast.Attribute) else f.id
    if name == "getenv":
        return call.args[0] if call.args else None
    if name in ("get", "setdefault", "pop") and \
            isinstance(f, ast.Attribute) and _environ_expr(f.value):
        return call.args[0] if call.args else None
    return None


class _DocIndex:
    def __init__(self, path, project):
        self.path = path
        try:
            self.relpath = str(path.relative_to(project.root))
        except ValueError:
            self.relpath = str(path)
        self.defined = {}        # var -> first definition line
        self.removed = set()
        self.mentioned = set()
        for lineno, line in enumerate(
                path.read_text(encoding="utf-8",
                               errors="replace").splitlines(), 1):
            vars_here = _VAR.findall(line)
            self.mentioned.update(vars_here)
            if not _DEF_ROW.match(line):
                continue
            first_cell = line.split("|")[1] if "|" in line else line
            for v in _VAR.findall(first_cell):
                self.defined.setdefault(v, lineno)
                if _REMOVED.search(line):
                    self.removed.add(v)


@register
class EnvDriftPass(LintPass):
    name = "env-drift"
    scope = "project"
    description = ("MXTPU_* read sites vs docs/env_vars.md: "
                   "undocumented reads and documented-but-dead knobs")

    def run_project(self, project):
        doc_path = project.find_contract_file("docs", "env_vars.md")
        doc = _DocIndex(doc_path, project) if doc_path is not None \
            else None
        reads = {}               # var -> [(relpath, lineno)]
        wrappers = self._find_wrappers(project)
        for relpath, module in sorted(project.modules.items()):
            if module.tree is None:
                continue
            for node in ast.walk(module.tree):
                var = line = None
                if isinstance(node, ast.Call):
                    key = _key_node(node)
                    if key is None:
                        key = self._wrapper_key(project, relpath,
                                                module, node, wrappers)
                    var, line = self._lit(key), node.lineno
                elif isinstance(node, ast.Subscript) and \
                        _environ_expr(node.value) and \
                        isinstance(node.ctx, ast.Load):
                    var, line = self._lit(node.slice), node.lineno
                elif isinstance(node, ast.Compare) and \
                        len(node.ops) == 1 and \
                        isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                        and _environ_expr(node.comparators[0]):
                    var, line = self._lit(node.left), node.lineno
                if var is not None and var.startswith("MXTPU_"):
                    reads.setdefault(var, []).append((relpath, line))
        out = []
        if doc is not None:
            for var, sites in sorted(reads.items()):
                if var in doc.defined:
                    continue
                for relpath, lineno in sites:
                    out.append(project.modules[relpath].finding(
                        _Line(lineno), self.name,
                        "%s is read here but has no definition row in "
                        "%s" % (var, doc.relpath)))
            if project.contract_is_closed(doc_path):
                test_reads = set()
                for text in project.test_corpus().values():
                    test_reads |= env_reads_in_text(text)
                for var, lineno in sorted(doc.defined.items()):
                    if var in reads or var in test_reads or \
                            var in doc.removed:
                        continue
                    out.append(Finding(
                        doc.relpath, lineno, 0, self.name,
                        "%s is documented but nothing reads it — "
                        "delete the row or mark it (removed)" % var,
                        text="", func="<doc>"))
        return out

    @staticmethod
    def _lit(node):
        return node.value if isinstance(node, ast.Constant) and \
            isinstance(node.value, str) else None

    # -- wrapper plumbing --------------------------------------------------
    def _find_wrappers(self, project):
        """{func key: key-param index} for functions whose parameter
        flows into a direct environ read."""
        out = {}
        for key, rec in project.funcs.items():
            params = [a.arg for a in rec.node.args.args]
            if rec.cls and params and params[0] == "self":
                params = params[1:]
                offset = 1
            else:
                offset = 0
            if not params:
                continue
            for node in ast.walk(rec.node):
                k = None
                if isinstance(node, ast.Call):
                    k = _key_node(node)
                elif isinstance(node, ast.Subscript) and \
                        _environ_expr(node.value):
                    k = node.slice
                if isinstance(k, ast.Name) and k.id in params:
                    out[key] = params.index(k.id) + offset
                    break
        return out

    def _wrapper_key(self, project, relpath, module, call, wrappers):
        if not wrappers:
            return None
        from ..project import classify_call
        kind = classify_call(call)
        if kind is None:
            return None
        caller = self._enclosing_class(module, call)
        target = project.resolve_callsite(relpath, caller, kind)
        if target is None or target not in wrappers:
            return None
        idx = wrappers[target]
        # a bound method call does not spell out self at the site
        rec = project.funcs.get(target)
        if rec is not None and rec.cls is not None and \
                kind[0] != "plain" and idx:
            idx -= 1
        return call.args[idx] if idx < len(call.args) else None

    @staticmethod
    def _enclosing_class(module, node):
        parents = module.parent_map()
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = parents.get(cur)
        return None


class _Line:
    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0
