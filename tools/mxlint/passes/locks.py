"""lock-order pass: build the whole-program lock-acquisition graph and
report cycles / inconsistent acquisition orders as potential deadlocks.

What a regex can never see — ``with self._lock:`` *nesting* — is the
whole pass:

1. **Lock discovery.** An attribute is a lock when the project assigns
   it from ``threading.Lock/RLock/Condition/Semaphore/
   BoundedSemaphore`` (``self._x = threading.Lock()``), or when its
   name matches the lock naming convention (``*lock*``, ``*guard*``,
   ``*_cv``, ``*mutex*``, ``*cond*``). A call to a method whose name
   matches ``*lock_for*`` / ``*get_lock*`` is a lock factory — its
   result counts as one logical lock token (all per-key locks collapse
   to one token, which is sound for ordering: two threads taking two
   *different* key locks in opposite orders cannot deadlock, but the
   collapsed token still catches key-lock-vs-other-lock inversions,
   and a *nested* key lock shows up as a self-cycle worth a look).

2. **Token identity.** ``self._x`` is scoped to the enclosing class —
   class-scoped tokens unify ACROSS modules, which is what lets a
   serving-side call into ``kvstore_async`` meet the kvstore's own
   acquisitions in one graph. ``other._x`` resolves to the single
   declaring class (preferring a same-module declarer), else to a
   module-scoped ``?`` token; bare local lock names scope to their
   function (two functions' locals are different locks unless threaded
   through a call, which the summaries model).

3. **Held-set tracking.** ``with tok:`` holds through the body
   (multiple items nest left to right); ``tok.acquire(...)`` holds
   until a matching ``tok.release()`` later in the same statement list
   or the end of the function. While H is held, acquiring t adds edges
   ``h -> t`` for every h in H.

4. **Interprocedural summaries.** While holding H, calling a function
   resolvable through the project symbol table — same-class methods
   (single-inheritance bases included), ``self.attr.m()`` through
   attribute-type inference (``self.attr = Cls(...)``), imported
   functions, then project-wide *unique* non-generic names — adds
   ``h -> t`` for every lock t the callee may *transitively* acquire.
   This is how a cross-module AB/BA inversion through a
   ``threading.Thread(target=...)`` entry point surfaces: each
   thread's body contributes its edges to the one global graph.

5. **Verdict.** Strongly-connected components of the edge graph with
   more than one token are inconsistent acquisition orders (the
   classic AB/BA inversion is the 2-cycle); a self-edge is a nested
   acquisition of one non-reentrant token. Each cycle is one finding
   per participating edge site, so individual sites can be pragma'd or
   baselined.
"""
from __future__ import annotations

import ast
import re

from ..core import LintPass, register
from ..project import classify_call

_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"))
_NAME_PAT = re.compile(r"lock|guard|mutex|cond|(^|_)cv$", re.IGNORECASE)
_FACTORY_PAT = re.compile(r"lock_for|get_lock", re.IGNORECASE)


def _attr_chain_root(node):
    while isinstance(node, ast.Attribute):
        node = node.value
    return node


class _FuncInfo:
    def __init__(self, node, relpath, qualname, cls):
        self.node = node
        self.relpath = relpath
        self.qualname = qualname
        self.cls = cls            # enclosing class name or None
        self.direct = set()       # lock tokens acquired directly
        self.calls = set()        # CallSite kind tuples (hashable)
        self.reach = None         # transitive token set

    @property
    def key(self):
        return (self.relpath, self.qualname)


class LockGraph:
    """Whole-program lock graph builder (kept separate from the pass so
    the fixture harness and tests can drive it directly). Resolution
    goes through the :class:`~mxlint.project.Project` symbol table."""

    def __init__(self, project):
        self.project = project
        self.lock_attrs = {}      # attr -> {(relpath, class)}
        self.funcs = {}           # (relpath, qualname) -> _FuncInfo
        self.edges = {}           # (a, b) -> [(relpath, line, qual)]

    # -- discovery ---------------------------------------------------------
    def build(self):
        mods = sorted(self.project.modules.items())
        for _, module in mods:
            if module.tree is not None:
                self._collect_lock_attrs(module)
        for _, module in mods:
            if module.tree is not None:
                self._add_module(module)
        self._finalize()
        return self

    def _collect_lock_attrs(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and isinstance(value.func, (ast.Attribute, ast.Name))):
                continue
            ctor = value.func.attr if isinstance(value.func, ast.Attribute) \
                else value.func.id
            if ctor not in _LOCK_CTORS:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    cls = self._enclosing_class(module, t)
                    self.lock_attrs.setdefault(t.attr, set()).add(
                        (module.relpath, cls or "?"))

    @staticmethod
    def _enclosing_class(module, node):
        parents = module.parent_map()
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = parents.get(cur)
        return None

    # -- token naming ------------------------------------------------------
    def _token_for(self, expr, info):
        """Lock token for an expression, or None when it is not
        lock-like. ``info`` carries the class of ``self`` and the
        function scope for local-name tokens."""
        cls = info.cls
        if isinstance(expr, ast.Call):
            f = expr.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name and _FACTORY_PAT.search(name):
                if isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self" and cls:
                    return "%s.%s()" % (cls, name)
                return "?[%s].%s()" % (info.relpath, name)
            return None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            declared = self.lock_attrs.get(attr)
            lockish = bool(declared) or bool(_NAME_PAT.search(attr))
            if not lockish:
                return None
            root = _attr_chain_root(expr)
            if isinstance(root, ast.Name) and root.id == "self" and cls:
                return "%s.%s" % (cls, attr)
            if declared:
                # non-self access: the single declaring class wins; on
                # a tie prefer a same-module declarer, else collapse to
                # a module-scoped token (over-reports, never hides)
                classes = {c for (_, c) in declared}
                if len(classes) == 1:
                    return "%s.%s" % (next(iter(classes)), attr)
                local = {c for (rel, c) in declared
                         if rel == info.relpath}
                if len(local) == 1:
                    return "%s.%s" % (next(iter(local)), attr)
            return "?[%s].%s" % (info.relpath, attr)
        if isinstance(expr, ast.Name) and _NAME_PAT.search(expr.id):
            # a bare local: scoped to this function — distinct
            # functions' locals are distinct locks
            return "local[%s:%s].%s" % (info.relpath, info.qualname,
                                        expr.id)
        if isinstance(expr, ast.Subscript):
            # e.g. self._ch_locks[i]: one token for the whole family
            return self._token_for(expr.value, info)
        return None

    # -- function harvesting ----------------------------------------------
    def _add_module(self, module):
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = module.qualname(node)
                cls = self._enclosing_class(module, node)
                info = _FuncInfo(node, module.relpath, qual, cls)
                self.funcs[info.key] = info
                self._walk_body(module, info, info.node.body, [])

    def _note_acquire(self, module, info, token, held, node):
        for h in held:
            self.edges.setdefault((h, token), []).append(
                (module.relpath, node.lineno, info.qualname))
        info.direct.add(token)

    def _walk_body(self, module, info, body, held):
        held = list(held)
        for stmt in body:
            self._walk_stmt(module, info, stmt, held)

    def _walk_stmt(self, module, info, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                     # nested defs analyzed separately
        if isinstance(stmt, ast.With):
            pushed = []
            for item in stmt.items:
                tok = self._token_for(item.context_expr, info)
                # calls inside the context expr still run
                self._scan_calls(info, item.context_expr)
                if tok is not None:
                    self._note_acquire(module, info, tok, held,
                                       item.context_expr)
                    held.append(tok)
                    pushed.append(tok)
            self._walk_body(module, info, stmt.body, held)
            for tok in pushed:
                held.remove(tok)
            return
        # explicit acquire()/release() pairs, tracked linearly
        call = self._stmt_call(stmt)
        if call is not None and isinstance(call.func, ast.Attribute):
            if call.func.attr == "acquire":
                tok = self._token_for(call.func.value, info)
                if tok is not None:
                    self._note_acquire(module, info, tok, held, call)
                    held.append(tok)
                    # still scan args (rare, but cheap)
                    for a in call.args:
                        self._scan_calls(info, a)
                    return
            elif call.func.attr == "release":
                tok = self._token_for(call.func.value, info)
                if tok is not None and tok in held:
                    held.remove(tok)
                    return
        # recurse into compound statements with the current held set
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self._walk_body(module, info, sub, held)
        for h in getattr(stmt, "handlers", []) or []:
            self._walk_body(module, info, h.body, held)
        # scan expressions of this statement for calls made while held
        self._scan_calls(info, stmt)

    @staticmethod
    def _stmt_call(stmt):
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            return stmt.value
        return None

    def _scan_calls(self, info, node):
        """Record every call this function makes (for the transitive
        lock summaries); the held-set edges for those calls are added
        by the second walk in :meth:`_finalize`."""
        for child in ast.walk(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if not isinstance(child, ast.Call):
                continue
            kind = classify_call(child)
            if kind is not None:
                info.calls.add(kind)

    # -- interprocedural summary ------------------------------------------
    def _resolve(self, info, kind):
        return self.project.resolve_callsite(info.relpath, info.cls,
                                             kind)

    def _reach(self, key, stack=()):
        info = self.funcs.get(key)
        if info is None:
            return set()
        if info.reach is not None:
            return info.reach
        if key in stack:
            return set(info.direct)
        out = set(info.direct)
        for kind in info.calls:
            target = self._resolve(info, kind)
            if target is not None:
                out |= self._reach(target, stack + (key,))
        info.reach = out
        return out

    def _finalize(self):
        """Second walk adding summary edges: while held-set H, a call
        to a resolvable callee adds H x reach(callee)."""
        for key, info in self.funcs.items():
            module = self.project.modules.get(key[0])
            if module is None:
                continue
            self._summary_walk(module, info, info.node.body, [])

    def _summary_walk(self, module, info, body, held):
        held = list(held)
        for stmt in body:
            self._summary_stmt(module, info, stmt, held)

    def _summary_stmt(self, module, info, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.With):
            pushed = []
            for item in stmt.items:
                tok = self._token_for(item.context_expr, info)
                self._summary_calls(module, info, item.context_expr, held)
                if tok is not None:
                    held.append(tok)
                    pushed.append(tok)
            self._summary_walk(module, info, stmt.body, held)
            for tok in pushed:
                held.remove(tok)
            return
        call = self._stmt_call(stmt)
        if call is not None and isinstance(call.func, ast.Attribute):
            if call.func.attr == "acquire":
                tok = self._token_for(call.func.value, info)
                if tok is not None:
                    held.append(tok)
                    return
            elif call.func.attr == "release":
                tok = self._token_for(call.func.value, info)
                if tok is not None and tok in held:
                    held.remove(tok)
                    return
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self._summary_walk(module, info, sub, held)
        for h in getattr(stmt, "handlers", []) or []:
            self._summary_walk(module, info, h.body, held)
        if held:
            self._summary_calls(module, info, stmt, held,
                                top_level_only=True)

    def _summary_calls(self, module, info, node, held,
                       top_level_only=False):
        if not held:
            return
        for child in ast.walk(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if not isinstance(child, ast.Call):
                continue
            if top_level_only and self._inside_nested_block(node, child):
                continue
            kind = classify_call(child)
            if kind is None:
                continue
            target = self._resolve(info, kind)
            if target is None:
                continue
            for tok in self._reach(target):
                for h in held:
                    if h != tok:
                        self.edges.setdefault((h, tok), []).append(
                            (module.relpath, child.lineno,
                             info.qualname))

    @staticmethod
    def _inside_nested_block(stmt, call):
        """True when ``call`` sits inside a nested compound body of
        ``stmt`` (those are visited by the statement recursion with
        their own held set; scanning them again would double-count)."""
        for field in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, field, None) or []:
                if call.lineno >= sub.lineno and \
                        call.lineno <= (sub.end_lineno or sub.lineno):
                    return True
        for h in getattr(stmt, "handlers", []) or []:
            for sub in h.body:
                if call.lineno >= sub.lineno and \
                        call.lineno <= (sub.end_lineno or sub.lineno):
                    return True
        return False

    # -- verdict -----------------------------------------------------------
    def cycles(self):
        """Strongly-connected components with >1 token, plus self-edges;
        returns ``[(tokens, edge_sites)]``."""
        graph = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index = {}
        low = {}
        on_stack = set()
        stack = []
        sccs = []
        counter = [0]

        # iterative Tarjan: the whole-program graph can be deep
        def strongconnect(root):
            work = [(root, iter(graph.get(root, ())))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(graph.get(w, ()))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[v])
                if low[v] == index[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == v:
                            break
                    sccs.append(comp)

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        out = []
        for comp in sccs:
            comp_set = set(comp)
            if len(comp) > 1:
                sites = []
                for (a, b), locs in sorted(self.edges.items()):
                    if a in comp_set and b in comp_set:
                        sites.append(((a, b), locs))
                out.append((sorted(comp_set), sites))
        for (a, b), locs in sorted(self.edges.items()):
            if a == b:
                out.append(([a], [((a, b), locs)]))
        return out


@register
class LockOrderPass(LintPass):
    name = "lock-order"
    scope = "project"
    description = ("whole-program lock-acquisition graph cycles / "
                   "inconsistent acquisition orders (potential "
                   "deadlocks)")

    def run_project(self, project):
        graph = LockGraph(project).build()
        out = []
        for tokens, sites in graph.cycles():
            if len(tokens) == 1:
                kind = ("nested acquisition of %s (self-deadlock if "
                        "non-reentrant; for a lock factory, a real "
                        "deadlock when both sites can name the same "
                        "key)" % tokens[0])
            else:
                kind = ("inconsistent lock order across {%s} — threads "
                        "taking these in opposite orders can deadlock"
                        % ", ".join(tokens))
            for (a, b), locs in sites:
                for (relpath, lineno, qual) in locs:
                    module = project.modules.get(relpath)
                    if module is None:
                        continue
                    f = module.finding(
                        _Anchor(lineno), self.name,
                        "%s; this site takes %s while holding %s"
                        % (kind, b, a))
                    f.func = qual
                    out.append(f)
        return out


class _Anchor:
    """Minimal node stand-in so ModuleInfo.finding can anchor a graph
    edge (the edge site is a line, not a single AST node)."""

    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0
