"""lock-order pass: build the per-function lock-acquisition graph and
report cycles / inconsistent acquisition orders as potential deadlocks.

What a regex can never see — ``with self._lock:`` *nesting* — is the
whole pass:

1. **Lock discovery.** An attribute is a lock when the module assigns it
   from ``threading.Lock/RLock/Condition/Semaphore/BoundedSemaphore``
   (``self._x = threading.Lock()``), or when its name matches the lock
   naming convention (``*lock*``, ``*guard*``, ``*_cv``, ``*mutex*``,
   ``*cond*``). A call to a method whose name matches ``*lock_for*`` /
   ``*get_lock*`` is a lock factory — its result counts as one logical
   lock token (all per-key locks collapse to one token, which is sound
   for ordering: two threads taking two *different* key locks in
   opposite orders cannot deadlock, but the collapsed token still
   catches key-lock-vs-other-lock inversions, and a *nested* key lock
   shows up as a self-cycle worth a look).

2. **Token identity.** ``self._x`` is scoped to the enclosing class.
   ``other._x`` resolves to the single class declaring ``_x`` as a lock
   when that is unambiguous, else to a shared ``?._x`` token (collapsing
   distinct locks can only over-report, never hide an inversion).

3. **Held-set tracking.** ``with tok:`` holds through the body (multiple
   items nest left to right); ``tok.acquire(...)`` holds until a
   matching ``tok.release()`` later in the same statement list or the
   end of the function. While H is held, acquiring t adds edges
   ``h -> t`` for every h in H.

4. **Call summaries.** While holding H, calling a function/method
   resolvable inside the analyzed file set adds ``h -> t`` for every
   lock t that callee may (transitively) acquire — so ``with
   self._lock_for(key): self._note_worker_push(...)`` contributes the
   ``key-lock -> workers-lock`` edge even though the nested acquisition
   is two calls deep. Methods resolve by name within the defining class
   first, then uniquely across the file set.

5. **Verdict.** Strongly-connected components of the edge graph with
   more than one token are inconsistent acquisition orders (the classic
   AB/BA inversion is the 2-cycle); a self-edge is a nested acquisition
   of one non-reentrant token. Each cycle is one finding per
   participating edge site, so individual sites can be pragma'd or
   baselined.
"""
from __future__ import annotations

import ast
import re

from ..core import LintPass, register

_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"))
_NAME_PAT = re.compile(r"lock|guard|mutex|cond|(^|_)cv$", re.IGNORECASE)
_FACTORY_PAT = re.compile(r"lock_for|get_lock", re.IGNORECASE)


def _attr_chain_root(node):
    while isinstance(node, ast.Attribute):
        node = node.value
    return node


class _FuncInfo:
    def __init__(self, node, qualname, cls):
        self.node = node
        self.qualname = qualname
        self.cls = cls            # enclosing class name or None
        self.direct = set()       # lock tokens acquired directly
        self.calls = set()        # (recv_kind, name): recv_kind in
        #                           ("self", "other", "plain")
        self.reach = None         # transitive token set


class LockGraph:
    """Per-module-set lock graph builder (kept separate from the pass so
    the fixture harness and tests can drive it directly)."""

    def __init__(self):
        self.lock_attrs = {}      # attr -> set of declaring classes
        self.funcs = {}           # qualname -> _FuncInfo
        self.by_name = {}         # bare name -> [qualname]
        self.by_class = {}        # (cls, name) -> qualname
        self.edges = {}           # (a, b) -> [(module, line, qual)]

    # -- discovery ---------------------------------------------------------
    def _collect_lock_attrs(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and isinstance(value.func, (ast.Attribute, ast.Name))):
                continue
            ctor = value.func.attr if isinstance(value.func, ast.Attribute) \
                else value.func.id
            if ctor not in _LOCK_CTORS:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    cls = self._enclosing_class(module, t)
                    self.lock_attrs.setdefault(t.attr, set()).add(
                        cls or "?")

    @staticmethod
    def _enclosing_class(module, node):
        parents = module.parent_map()
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = parents.get(cur)
        return None

    # -- token naming ------------------------------------------------------
    def _token_for(self, expr, cls):
        """Lock token for an expression, or None when it is not
        lock-like. ``cls`` is the class of ``self`` at this site."""
        if isinstance(expr, ast.Call):
            f = expr.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name and _FACTORY_PAT.search(name):
                owner = cls if (isinstance(f, ast.Attribute)
                                and isinstance(f.value, ast.Name)
                                and f.value.id == "self") else "?"
                return "%s.%s()" % (owner or "?", name)
            return None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            declared = self.lock_attrs.get(attr)
            lockish = bool(declared) or bool(_NAME_PAT.search(attr))
            if not lockish:
                return None
            root = _attr_chain_root(expr)
            if isinstance(root, ast.Name) and root.id == "self" and cls:
                return "%s.%s" % (cls, attr)
            if declared and len(declared) == 1:
                return "%s.%s" % (next(iter(declared)), attr)
            return "?.%s" % attr
        if isinstance(expr, ast.Name) and _NAME_PAT.search(expr.id):
            return "local.%s" % expr.id
        if isinstance(expr, ast.Subscript):
            # e.g. self._ch_locks[i]: one token for the whole family
            return self._token_for(expr.value, cls)
        return None

    # -- function harvesting ----------------------------------------------
    def add_module(self, module):
        self._collect_lock_attrs(module)
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = module.qualname(node)
                cls = self._enclosing_class(module, node)
                info = _FuncInfo(node, qual, cls)
                self.funcs[(module.relpath, qual)] = info
                self.by_name.setdefault(node.name, []).append(
                    (module.relpath, qual))
                if cls:
                    self.by_class[(cls, node.name)] = \
                        (module.relpath, qual)
                self._walk_function(module, info)

    def _walk_function(self, module, info):
        self._walk_body(module, info, info.node.body, [])

    def _note_acquire(self, module, info, token, held, node):
        for h in held:
            if h == token and h.endswith("()"):
                # distinct keys of one factory are distinct locks; a
                # nested factory acquisition is only *potentially* a
                # self-deadlock, so record it but let the verdict
                # message say so
                pass
            self.edges.setdefault((h, token), []).append(
                (module.relpath, node.lineno, info.qualname))
        info.direct.add(token)

    def _walk_body(self, module, info, body, held):
        held = list(held)
        for stmt in body:
            self._walk_stmt(module, info, stmt, held)

    def _walk_stmt(self, module, info, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                     # nested defs analyzed separately
        if isinstance(stmt, ast.With):
            pushed = []
            for item in stmt.items:
                tok = self._token_for(item.context_expr, info.cls)
                # calls inside the context expr still run
                self._scan_calls(module, info, item.context_expr, held)
                if tok is not None:
                    self._note_acquire(module, info, tok, held,
                                       item.context_expr)
                    held.append(tok)
                    pushed.append(tok)
            self._walk_body(module, info, stmt.body, held)
            for tok in pushed:
                held.remove(tok)
            return
        # explicit acquire()/release() pairs, tracked linearly
        call = self._stmt_call(stmt)
        if call is not None and isinstance(call.func, ast.Attribute):
            if call.func.attr == "acquire":
                tok = self._token_for(call.func.value, info.cls)
                if tok is not None:
                    self._note_acquire(module, info, tok, held, call)
                    held.append(tok)
                    # still scan args (rare, but cheap)
                    for a in call.args:
                        self._scan_calls(module, info, a, held)
                    return
            elif call.func.attr == "release":
                tok = self._token_for(call.func.value, info.cls)
                if tok is not None and tok in held:
                    held.remove(tok)
                    return
        # recurse into compound statements with the current held set
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self._walk_body(module, info, sub, held)
        for h in getattr(stmt, "handlers", []) or []:
            self._walk_body(module, info, h.body, held)
        # scan expressions of this statement for calls made while held
        self._scan_calls(module, info, stmt, held, skip_bodies=True)

    @staticmethod
    def _stmt_call(stmt):
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            return stmt.value
        return None

    def _scan_calls(self, module, info, node, held, skip_bodies=False):
        """Record every call this function makes (for the transitive
        lock summaries); the held-set edges for those calls are added by
        the second walk in :meth:`finalize`."""
        for child in ast.walk(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if not isinstance(child, ast.Call):
                continue
            f = child.func
            if isinstance(f, ast.Attribute):
                if isinstance(f.value, ast.Name) and f.value.id == "self":
                    info.calls.add(("self", f.attr, child.lineno))
                else:
                    info.calls.add(("other", f.attr, child.lineno))
            elif isinstance(f, ast.Name):
                info.calls.add(("plain", f.id, child.lineno))

    # -- interprocedural summary ------------------------------------------
    # method names shared with the threading/queue primitives: a call
    # like ``cv.wait()`` must never resolve to an unrelated same-named
    # method in this file (it would fabricate lock edges)
    _GENERIC = frozenset((
        "wait", "join", "get", "put", "set", "clear", "notify",
        "notify_all", "acquire", "release", "is_set", "result",
        "append", "pop", "items", "values", "keys", "update", "add",
        "discard", "remove", "copy", "close", "start"))

    def _resolve(self, info, kind, name):
        if kind == "self" and info.cls and \
                (info.cls, name) in self.by_class:
            return self.by_class[(info.cls, name)]
        if kind != "plain" and name in self._GENERIC:
            return None
        cands = self.by_name.get(name, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def _reach(self, key, stack=()):
        info = self.funcs.get(key)
        if info is None:
            return set()
        if info.reach is not None:
            return info.reach
        if key in stack:
            return set(info.direct)
        out = set(info.direct)
        for entry in info.calls:
            kind, name = entry[0], entry[1]
            target = self._resolve(info, kind, name)
            if target is not None:
                out |= self._reach(target, stack + (key,))
        info.reach = out
        return out

    def finalize(self, modules_by_path):
        """Second walk adding summary edges: while held-set H, a call to
        a resolvable callee adds H x reach(callee)."""
        for key, info in self.funcs.items():
            module = modules_by_path.get(key[0])
            if module is None:
                continue
            self._summary_walk(module, info, info.node.body, [])

    def _summary_walk(self, module, info, body, held):
        held = list(held)
        for stmt in body:
            self._summary_stmt(module, info, stmt, held)

    def _summary_stmt(self, module, info, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.With):
            pushed = []
            for item in stmt.items:
                tok = self._token_for(item.context_expr, info.cls)
                self._summary_calls(module, info, item.context_expr, held)
                if tok is not None:
                    held.append(tok)
                    pushed.append(tok)
            self._summary_walk(module, info, stmt.body, held)
            for tok in pushed:
                held.remove(tok)
            return
        call = self._stmt_call(stmt)
        if call is not None and isinstance(call.func, ast.Attribute):
            if call.func.attr == "acquire":
                tok = self._token_for(call.func.value, info.cls)
                if tok is not None:
                    held.append(tok)
                    return
            elif call.func.attr == "release":
                tok = self._token_for(call.func.value, info.cls)
                if tok is not None and tok in held:
                    held.remove(tok)
                    return
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self._summary_walk(module, info, sub, held)
        for h in getattr(stmt, "handlers", []) or []:
            self._summary_walk(module, info, h.body, held)
        if held:
            self._summary_calls(module, info, stmt, held,
                                top_level_only=True)

    def _summary_calls(self, module, info, node, held,
                       top_level_only=False):
        if not held:
            return
        for child in ast.walk(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if not isinstance(child, ast.Call):
                continue
            if top_level_only and self._inside_nested_block(node, child):
                continue
            f = child.func
            if isinstance(f, ast.Attribute):
                kind = "self" if (isinstance(f.value, ast.Name)
                                  and f.value.id == "self") else "other"
                name = f.attr
            elif isinstance(f, ast.Name):
                kind, name = "plain", f.id
            else:
                continue
            target = self._resolve(info, kind, name)
            if target is None:
                continue
            for tok in self._reach(target):
                for h in held:
                    if h != tok:
                        self.edges.setdefault((h, tok), []).append(
                            (module.relpath, child.lineno,
                             info.qualname))

    @staticmethod
    def _inside_nested_block(stmt, call):
        """True when ``call`` sits inside a nested compound body of
        ``stmt`` (those are visited by the statement recursion with
        their own held set; scanning them again would double-count)."""
        for field in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, field, None) or []:
                if call.lineno >= sub.lineno and \
                        call.lineno <= (sub.end_lineno or sub.lineno):
                    return True
        for h in getattr(stmt, "handlers", []) or []:
            for sub in h.body:
                if call.lineno >= sub.lineno and \
                        call.lineno <= (sub.end_lineno or sub.lineno):
                    return True
        return False

    # -- verdict -----------------------------------------------------------
    def cycles(self):
        """Strongly-connected components with >1 token, plus self-edges;
        returns ``[(tokens, edge_sites)]``."""
        graph = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index = {}
        low = {}
        on_stack = set()
        stack = []
        sccs = []
        counter = [0]

        def strongconnect(v):
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in graph.get(v, ()):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)

        for v in list(graph):
            if v not in index:
                strongconnect(v)
        out = []
        for comp in sccs:
            comp_set = set(comp)
            if len(comp) > 1:
                sites = []
                for (a, b), locs in sorted(self.edges.items()):
                    if a in comp_set and b in comp_set:
                        sites.append(((a, b), locs))
                out.append((sorted(comp_set), sites))
        for (a, b), locs in sorted(self.edges.items()):
            if a == b:
                out.append(([a], [((a, b), locs)]))
        return out


@register
class LockOrderPass(LintPass):
    name = "lock-order"
    description = ("lock-acquisition graph cycles / inconsistent "
                   "acquisition orders (potential deadlocks)")

    def run(self, module):
        # the graph is meaningful per file: cross-file lock sharing in
        # this tree happens through objects analyzed in their defining
        # file (kvstore_async holds every party of its protocol)
        graph = LockGraph()
        graph.add_module(module)
        graph.finalize({module.relpath: module})
        out = []
        for tokens, sites in graph.cycles():
            if len(tokens) == 1:
                kind = ("nested acquisition of %s (self-deadlock if "
                        "non-reentrant; for a lock factory, a real "
                        "deadlock when both sites can name the same "
                        "key)" % tokens[0])
            else:
                kind = ("inconsistent lock order across {%s} — threads "
                        "taking these in opposite orders can deadlock"
                        % ", ".join(tokens))
            for (a, b), locs in sites:
                for (relpath, lineno, qual) in locs:
                    f = module.finding(
                        _Anchor(lineno), self.name,
                        "%s; this site takes %s while holding %s"
                        % (kind, b, a))
                    f.func = qual
                    out.append(f)
        return out


class _Anchor:
    """Minimal node stand-in so ModuleInfo.finding can anchor a graph
    edge (the edge site is a line, not a single AST node)."""

    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0
