"""resource-leak pass: sockets, threads and subprocesses created and
then abandoned in one function.

The fleet's teardown bugs (PR 6's zombie launchers, nightly drivers
leaking probe sockets) share one AST shape: a resource constructor
bound to a plain local that the function neither closes, joins,
returns, stores nor hands to anyone else. That narrow shape is what
this pass flags — anything that *escapes* the function (returned,
assigned to an attribute or container, passed as an argument, bound
via ``with``) is presumed managed elsewhere, so the pass stays quiet
on factories and registries by construction:

* ``socket.socket()`` / ``socket.create_connection()`` locals need a
  ``.close()`` (or a ``with`` block) on some path;
* ``threading.Thread(...)`` locals need ``.join()`` unless created
  ``daemon=True`` (a daemon thread's lifetime is the process's);
* ``subprocess.Popen(...)`` locals need a ``wait``/``communicate``/
  ``terminate``/``kill``.

Escape analysis is per-function and name-based — deliberately simple;
the point is the fire-and-forget constructor, not a full alias
analysis.
"""
from __future__ import annotations

import ast

from ..core import LintPass, register

_CLEANUP = {
    "socket": frozenset(("close", "detach", "shutdown")),
    "thread": frozenset(("join",)),
    "popen": frozenset(("wait", "communicate", "terminate", "kill",
                        "poll")),
}
_CTORS = {
    "socket": "socket", "create_connection": "socket",
    "Thread": "thread", "Timer": "thread", "Popen": "popen",
}
_NOUN = {"socket": "socket", "thread": "thread", "popen": "subprocess"}


def _ctor_kind(call):
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return _CTORS.get(name)


def _daemon_true(call):
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and bool(kw.value.value):
            return True
    return False


@register
class ResourceLeakPass(LintPass):
    name = "resource-leak"
    description = ("socket/thread/subprocess locals with no close/"
                   "join/wait on any path and no escape from the "
                   "function")

    def run(self, module):
        out = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_function(module, node))
        return out

    @staticmethod
    def _own_nodes(fn):
        """Walk ``fn`` without descending into nested defs/lambdas
        (their locals are their own scope, checked separately) — but a
        nested def still *sees* the enclosing locals, so closures are
        scanned for cleanup/escape by the caller below."""
        stack = [fn]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                stack.append(child)

    def _check_function(self, module, fn):
        created = {}             # local name -> (kind, ctor node)
        for stmt in self._own_nodes(fn):
            if not isinstance(stmt, ast.Assign):
                continue
            if not (isinstance(stmt.value, ast.Call)):
                continue
            kind = _ctor_kind(stmt.value)
            if kind is None:
                continue
            if kind == "thread" and _daemon_true(stmt.value):
                continue
            if len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                created[stmt.targets[0].id] = (kind, stmt.value)
        if not created:
            return []
        cleaned, escaped = set(), set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in created:
                    kind = created[f.value.id][0]
                    if f.attr in _CLEANUP[kind]:
                        cleaned.add(f.value.id)
                for a in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                    if isinstance(a, ast.Name) and a.id in created:
                        escaped.add(a.id)
            elif isinstance(node, ast.Return) and \
                    node.value is not None:
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name) and n.id in created:
                        escaped.add(n.id)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                src = node.value
                names = {n.id for n in ast.walk(src)
                         if isinstance(n, ast.Name)}
                targets = node.targets \
                    if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if not isinstance(t, ast.Name):
                        # self.x = sock / d[k] = sock: escapes
                        escaped.update(names & set(created))
            elif isinstance(node, ast.withitem):
                expr = node.context_expr
                if isinstance(expr, ast.Name) and expr.id in created:
                    cleaned.add(expr.id)
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) and \
                    node.value is not None:
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name) and n.id in created:
                        escaped.add(n.id)
        out = []
        for name, (kind, ctor) in sorted(created.items()):
            if name in cleaned or name in escaped:
                continue
            out.append(module.finding(
                ctor, self.name,
                "%s %r is created here but never %s and never leaves "
                "this function — it leaks on every path"
                % (_NOUN[kind], name,
                   "/".join(sorted(_CLEANUP[kind])[:2]) + "'d")))
        return out
