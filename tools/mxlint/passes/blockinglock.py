"""blocking-under-lock pass: a blocking wait while a lock is held
parks every other thread that needs the lock for as long as the wait
takes — the exact failure mode that turns one slow peer into a fleet
stall. Built on the same per-statement held-lockset machinery as
``shared-state-race``.

Flagged while the effective lockset (directly held + the one-level
caller context) is non-empty:

* socket waits — ``.recv(`` / ``.recv_into(`` / ``.accept(`` /
  ``.connect(`` / ``create_connection`` / ``select``;
* condition/event waits — ``.wait()`` / ``.wait_for()`` — EXCEPT a
  wait on a condition whose own lock is the only thing held (that is
  the idiom: ``Condition.wait`` releases its lock while parked);
* queue hand-offs — ``.get()`` (no positional args — ``dict.get(k)``
  never matches) and ``.put(...)``;
* ``.join()``, ``time.sleep``, ``future.result()``.

Bounded waits are flagged too: ``q.get(timeout=0.1)`` under a lock
still stalls that lock's waiters for the timeout — the existing
``blocking-call`` pass owns the unbounded-wait question; this pass
owns the held-lock question. ``send``/``sendall`` are deliberately not
flagged: a per-socket sender thread writing under its wire lock is the
transport's design.

A deliberate hold-across-wait (e.g. a handoff that must keep its key
lock across a peer RPC for exactly-once semantics) carries
``# mxlint: allow(blocking-under-lock) — <why>``; the reason is
mandatory.
"""
from __future__ import annotations

from ..core import LintPass, register
from ..locksets import lockset_model


@register
class BlockingUnderLockPass(LintPass):
    name = "blocking-under-lock"
    scope = "project"
    description = ("blocking socket/condition/queue wait while a lock "
                   "is held (stalls every waiter on that lock)")

    def run_project(self, project):
        model = lockset_model(project)
        out = []
        for (site, eff) in model.blocking_sites():
            module = project.modules.get(site.relpath)
            if module is None:
                continue
            f = module.finding(
                _Anchor(site.lineno), self.name,
                "blocking .%s() while holding {%s} — every thread "
                "needing %s lock stalls for the duration of the wait"
                % (site.name, ", ".join(sorted(eff)),
                   "that" if len(eff) == 1 else "any held"))
            f.func = site.func_key[1]
            out.append(f)
        return out


class _Anchor:
    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0
