"""wire-protocol pass: the op and verdict string sets of the fleet's
wire protocols must agree between the servers that speak them and the
clients that listen.

The protocols under analysis are tuple-frame RPCs (kvstore_async, the
serving wire): a request is ``("<op>", ...)``, a reply is
``("<verdict>", ...)`` where verdicts beyond ``ok``/``err`` steer
client routing (``overloaded``, ``draining``, ``expired``,
``not_serving``/``map_stale`` inside err strings). None of this is
typed — the contract lives in string literals on both sides of the
wire, which is exactly what drifts silently when a server grows a new
verdict nobody handles, or a handler outlives the last emitter.

Extraction (all whole-program, over the project symbol table):

* **Dispatchers** — a function assigning ``cmd``/``op``/``command``
  from element 0 of a frame (``cmd = msg[0]``) and comparing it
  against 2+ string literals. Those literals are the *dispatched op
  set* (membership tests against literal tuples count too).
* **Requested ops** — string literals in the first argument of a
  ``*request*``-named call (``conn.request("hello", ...)``,
  ``self._peer_request("peer_info")``), plus tuple-literal items of a
  ``request_all`` batch. Looser *evidence* that an op is alive — a
  tuple literal ``("push", ...)`` anywhere, or the literal appearing
  as any call argument — only absolves a handler, it is never strong
  enough to demand a handler.
* **Emitted verdicts** — in *server modules* (a module containing a
  dispatcher, plus modules whose classes a dispatcher module
  instantiates as components, e.g. the serving batcher): the string
  head of a tuple literal in return position, in a ``resolve(...)``
  reply, or in a module-level constant (``_NO_REPLY``); plus the
  ``tok`` of every ``("err", "tok: ...")`` reply — the kvstore's
  routing sub-verdicts.
* **Handled verdicts** — comparisons of a ``verdict``-named variable
  or a ``reply[0]``-style subscript against string literals,
  membership tests against literal tuples, substring guards
  (``"not_serving" in str(e)``) and ``re.search("map_stale: ...")``
  patterns.

Findings:

* an emitted verdict (beyond built-in ``ok``/``err``) with **no
  handler anywhere** — the server speaks a word no client knows;
* a requested op **no dispatcher serves** — the request can only come
  back ``err``;
* in closed/whole-tree runs additionally the dead-code directions: a
  *handler* for a verdict nothing emits, and a *dispatched op* nothing
  requests.
"""
from __future__ import annotations

import ast
import re

from ..core import LintPass, register

_TOKEN = re.compile(r"^[a-z_][a-z0-9_]*$")
_DISPATCH_VARS = frozenset(("cmd", "op", "command", "opcode"))
_BUILTIN_VERDICTS = frozenset(("ok", "err"))
_REPLY_BASES = re.compile(r"reply|resp|verdict|^r$")


def _tok(value):
    return isinstance(value, str) and bool(_TOKEN.match(value))


def _str_const(node):
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None


def _iter_funcs(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class _Protocol:
    """Everything extracted from one project, site-anchored."""

    def __init__(self):
        self.dispatched = {}      # op -> [(relpath, lineno)]
        self.requested = {}       # op -> [(relpath, lineno)]
        self.evidence = set()     # loose liveness evidence for ops
        self.emitted = {}         # verdict -> [(relpath, lineno)]
        self.err_texts = []       # literal err reply texts
        self.handled = set()      # broad: any handling literal
        self.handler_sites = {}   # narrow: verdict -> [(relpath, line)]
        self.substr_sites = {}    # substring guards -> [(relpath, ln)]
        self.dispatcher_modules = set()
        self.client_modules = set()


def _sub_verdict(text):
    """``not_serving`` out of ``"not_serving: shard replica ..."``."""
    head, sep, _ = text.partition(":")
    if sep and _tok(head):
        return head
    return None


@register
class WireProtocolPass(LintPass):
    name = "wire-protocol"
    scope = "project"
    description = ("op/verdict drift between wire servers and their "
                   "clients (unhandled verdicts, unserved requests, "
                   "dead handlers)")

    # -- extraction --------------------------------------------------------
    def _extract(self, project):
        proto = _Protocol()
        for relpath, module in sorted(project.modules.items()):
            if module.tree is None:
                continue
            self._extract_module(relpath, module, proto)
        self._extract_components(project, proto)
        return proto

    def _extract_module(self, relpath, module, proto):
        tree = module.tree
        for fn in _iter_funcs(tree):
            ops = self._dispatcher_ops(fn)
            if ops:
                proto.dispatcher_modules.add(relpath)
                for op, line in ops:
                    proto.dispatched.setdefault(op, []).append(
                        (relpath, line))
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._scan_request_call(relpath, node, proto)
            elif isinstance(node, ast.Tuple):
                head = _str_const(node.elts[0]) if node.elts else None
                if head is not None:
                    proto.evidence.add(head)
            elif isinstance(node, ast.Compare):
                self._scan_compare(relpath, node, proto)
        if relpath in proto.client_modules or \
                self._has_strict_request(tree):
            proto.client_modules.add(relpath)

    def _dispatcher_ops(self, fn):
        """``[(op, lineno)]`` when ``fn`` is a frame dispatcher, else
        []: it assigns a ``cmd``/``op`` variable from ``<frame>[0]``
        and compares it against >= 2 string literals."""
        dvars = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not (isinstance(node.value, ast.Subscript)
                    and isinstance(node.value.slice, ast.Constant)
                    and node.value.slice.value == 0):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in _DISPATCH_VARS:
                    dvars.add(t.id)
        if not dvars:
            return []
        ops = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare) or len(node.ops) != 1:
                continue
            left, right = node.left, node.comparators[0]
            if isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                for lit, var in ((left, right), (right, left)):
                    v = _str_const(lit)
                    if v is not None and isinstance(var, ast.Name) \
                            and var.id in dvars and _tok(v):
                        ops.append((v, node.lineno))
            elif isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                    isinstance(left, ast.Name) and left.id in dvars and \
                    isinstance(right, (ast.Tuple, ast.List, ast.Set)):
                for e in right.elts:
                    v = _str_const(e)
                    if v is not None and _tok(v):
                        ops.append((v, node.lineno))
        return ops if len({o for o, _ in ops}) >= 2 else []

    @staticmethod
    def _has_strict_request(tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    "request" in node.func.attr and node.args and \
                    _str_const(node.args[0]) is not None:
                return True
        return False

    def _scan_request_call(self, relpath, node, proto):
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name is None:
            return
        if "request" in name and node.args:
            v = _str_const(node.args[0])
            if v is not None and _tok(v):
                if name == "request_all":
                    proto.evidence.add(v)
                else:
                    proto.requested.setdefault(v, []).append(
                        (relpath, node.lineno))
            elif name == "request_all" and \
                    isinstance(node.args[0], (ast.List, ast.Tuple)):
                for e in node.args[0].elts:
                    if isinstance(e, ast.Tuple) and e.elts:
                        v = _str_const(e.elts[0])
                        if v is not None and _tok(v):
                            proto.requested.setdefault(v, []).append(
                                (relpath, node.lineno))
        # any literal op riding any call keeps a handler alive
        for a in node.args:
            v = _str_const(a)
            if v is not None:
                proto.evidence.add(v)
        # re.search("map_stale: ...") / substring handling guards
        if name in ("search", "match", "fullmatch") and node.args:
            v = _str_const(node.args[0])
            if v is not None:
                sub = _sub_verdict(v)
                if sub is not None:
                    proto.handled.add(sub)
                    proto.substr_sites.setdefault(sub, []).append(
                        (relpath, node.lineno))

    def _scan_compare(self, relpath, node, proto):
        if len(node.ops) != 1:
            return
        left, right = node.left, node.comparators[0]

        def is_reply_expr(x, narrow):
            if isinstance(x, ast.Name):
                return bool(_REPLY_BASES.search(x.id)) or \
                    (not narrow and x.id in _DISPATCH_VARS)
            if isinstance(x, ast.Subscript) and \
                    isinstance(x.slice, ast.Constant) and \
                    x.slice.value == 0:
                base = x.value
                if narrow:
                    return isinstance(base, ast.Name) and \
                        bool(_REPLY_BASES.search(base.id))
                return True
            return False

        if isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            for lit, var in ((left, right), (right, left)):
                v = _str_const(lit)
                if v is None or not _tok(v):
                    continue
                if is_reply_expr(var, narrow=False):
                    proto.handled.add(v)
                if is_reply_expr(var, narrow=True):
                    proto.handler_sites.setdefault(v, []).append(
                        (relpath, node.lineno))
        elif isinstance(node.ops[0], (ast.In, ast.NotIn)):
            if isinstance(right, (ast.Tuple, ast.List, ast.Set)):
                for e in right.elts:
                    v = _str_const(e)
                    if v is None or not _tok(v):
                        continue
                    if is_reply_expr(left, narrow=False):
                        proto.handled.add(v)
                    if is_reply_expr(left, narrow=True):
                        proto.handler_sites.setdefault(v, []).append(
                            (relpath, node.lineno))
            elif isinstance(right, ast.Call):
                # "not_serving" in str(e): substring-shaped handling
                v = _str_const(left)
                fn = right.func
                if v is not None and _tok(v) and \
                        isinstance(fn, ast.Name) and fn.id == "str":
                    proto.handled.add(v)
                    proto.substr_sites.setdefault(v, []).append(
                        (relpath, node.lineno))

    # -- emit scope --------------------------------------------------------
    def _extract_components(self, project, proto):
        scope = set(proto.dispatcher_modules)
        for relpath in proto.dispatcher_modules:
            for recs in project.classes.values():
                for crec in recs:
                    if crec.relpath != relpath:
                        continue
                    for tname in crec.attr_types.values():
                        for trec in project.classes.get(tname, ()):
                            scope.add(trec.relpath)
        for relpath in sorted(scope):
            module = project.modules.get(relpath)
            if module is None or module.tree is None:
                continue
            self._extract_emits(relpath, module, proto)

    def _emit_tuple(self, relpath, node, proto):
        if not (isinstance(node, ast.Tuple) and node.elts):
            return
        head = _str_const(node.elts[0])
        if head is None or not _tok(head):
            return
        proto.emitted.setdefault(head, []).append(
            (relpath, node.lineno))
        if head == "err" and len(node.elts) > 1:
            second = node.elts[1]
            if isinstance(second, ast.BinOp) and \
                    isinstance(second.op, ast.Mod):
                second = second.left
            text = _str_const(second)
            if text is not None:
                proto.err_texts.append(text)
                sub = _sub_verdict(text)
                if sub is not None:
                    proto.emitted.setdefault(sub, []).append(
                        (relpath, node.lineno))

    def _extract_emits(self, relpath, module, proto):
        tree = module.tree
        for node in ast.walk(tree):
            if isinstance(node, ast.Return) and node.value is not None:
                vals = [node.value]
                if isinstance(node.value, ast.IfExp):
                    vals = [node.value.body, node.value.orelse]
                for v in vals:
                    self._emit_tuple(relpath, v, proto)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "resolve":
                for a in node.args:
                    self._emit_tuple(relpath, a, proto)
        # module-level reply constants (the _NO_REPLY sentinel)
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                self._emit_tuple(relpath, stmt.value, proto)

    # -- verdicts ----------------------------------------------------------
    def run_project(self, project):
        proto = self._extract(project)
        out = []

        def emit(relpath, lineno, message):
            module = project.modules.get(relpath)
            if module is None:
                return
            out.append(module.finding(_Line(lineno), self.name,
                                      message))

        for verdict, sites in sorted(proto.emitted.items()):
            if verdict in _BUILTIN_VERDICTS or verdict in proto.handled:
                continue
            for relpath, lineno in sites:
                emit(relpath, lineno,
                     "verdict %r is emitted on the wire but no client "
                     "handles it (checked ==/in comparisons, substring "
                     "guards and regexes project-wide)" % verdict)
        if proto.dispatched:
            for op, sites in sorted(proto.requested.items()):
                if op in proto.dispatched:
                    continue
                for relpath, lineno in sites:
                    emit(relpath, lineno,
                         "op %r is requested but no dispatcher serves "
                         "it — this request can only come back err"
                         % op)
        if project.closed:
            alive = set(proto.evidence) | set(proto.requested)
            for op, sites in sorted(proto.dispatched.items()):
                if op in alive:
                    continue
                for relpath, lineno in sites:
                    emit(relpath, lineno,
                         "op %r has a dispatch arm but nothing in the "
                         "program ever sends it — dead wire handler"
                         % op)
            emitted = set(proto.emitted) | _BUILTIN_VERDICTS
            for verdict, sites in sorted(proto.handler_sites.items()):
                if verdict in emitted or verdict in proto.dispatched \
                        or verdict in proto.evidence:
                    continue
                for relpath, lineno in sites:
                    if relpath not in proto.client_modules:
                        continue
                    emit(relpath, lineno,
                         "handler for verdict %r but no server emits "
                         "it — dead verdict handler" % verdict)
            # a substring guard is alive while its text still appears
            # in some emitted err reply
            for verdict, sites in sorted(proto.substr_sites.items()):
                if verdict in emitted or verdict in proto.evidence or \
                        any(verdict in t for t in proto.err_texts):
                    continue
                for relpath, lineno in sites:
                    if relpath not in proto.client_modules:
                        continue
                    emit(relpath, lineno,
                         "substring guard for %r matches no emitted "
                         "err reply — dead verdict handler" % verdict)
        return out


class _Line:
    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0
