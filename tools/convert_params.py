#!/usr/bin/env python
"""Convert parameter files between the reference's binary .params format
and mxtpu's container (either direction; the model-zoo migration path,
reference gluon/model_zoo/model_store.py downloads + mx.nd.load).

  # reference-trained checkpoint -> mxtpu
  python tools/convert_params.py resnet50-0000.params out.params

  # mxtpu weights -> a file reference deployments can read
  python tools/convert_params.py trained.params legacy.params --to-legacy

Gluon model-zoo naming (e.g. resnetv10_conv0_weight) matches between the
frameworks, so converted zoo weights load straight into
mxtpu.gluon.model_zoo networks via net.load_params. Symbol checkpoints
keep their arg:/aux: key prefixes untouched.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx  # noqa: E402
from mxtpu.legacy_params import save_legacy_params  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("src")
    ap.add_argument("dst")
    ap.add_argument("--to-legacy", action="store_true",
                    help="write the reference binary format instead of "
                         "mxtpu's")
    args = ap.parse_args()

    data = mx.nd.load(args.src)   # sniffs either format
    if args.to_legacy:
        save_legacy_params(args.dst, data)
    else:
        mx.nd.save(args.dst, data)
    n = len(data)
    print("converted %d arrays: %s -> %s%s" % (
        n, args.src, args.dst,
        " (reference binary format)" if args.to_legacy else ""))


if __name__ == "__main__":
    main()
