#!/usr/bin/env python
"""Loopback serving bench: offered load vs latency and shed rate.

The serving acceptance surface (ISSUE 8; numbers land in
docs/perf_analysis.md "Serving"): one in-process ModelServer over a
tiny-MLP checkpoint, swept by closed-loop concurrent clients — each
level doubles the offered load by doubling the concurrent client count
(every client is its own ServingClient with its own connection,
issuing --iters back-to-back predicts). Per level:

* achieved throughput (req/s, rows/s) and request latency p50/p99;
* shed rate: the fraction of attempts refused with the retriable
  ``overloaded`` verdict once the offered load outruns the queue;
* batching effectiveness: device batches vs requests, average rows per
  dispatch (the dynamic-batching win: device dispatches grow sublinearly
  with load).

The headline sweep runs the default transport (the MXTPU_PS_LOCAL
same-process shortcut — this bench's server IS in-process); the "tcp"
sub-object repeats the middle level over real loopback framing. The
steady-state sweep also proves the zero-retrace contract: program
compiles after warmup stay flat (the AOT bucket menu absorbs every
request shape).

The "generate" sub-object is the continuous-batching sweep (ISSUE 17):
a tiny attention LM checkpoint served through the generate path, each
level N concurrent greedy sequences — tokens/s per level, TTFT and
per-decode-step p50/p99 from the ``serve.gen.ttft_ms`` /
``serve.gen.step_ms`` registry histograms' per-level bucket deltas,
and the batching win (tokens/s at the top level over the bottom one,
the number ci/check_generate_perf.py pins at >= 2x for 64 vs 8).

Prints exactly ONE JSON line (tests/test_bench_contract.py parses it)
and mirrors it to docs/serving_bench.json unless --no-write. CPU-only.

Run: JAX_PLATFORMS=cpu python tools/bench_serving.py
     [--clients 8,64,256] [--iters 20] [--max-new 32]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, ROOT)


def _pct(samples, q):
    s = sorted(samples)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def _hist_counts(name):
    """(bounds, summed bucket counts) of one registry histogram family
    — the server-side latency instruments (ISSUE 14)."""
    from mxtpu import obs
    fam = obs.REGISTRY.snapshot()["metrics"].get(name)
    if not fam or fam["kind"] != "histogram":
        return None, None
    counts = None
    for s in fam["series"].values():
        counts = list(s["buckets"]) if counts is None else \
            [a + b for a, b in zip(counts, s["buckets"])]
    from mxtpu.obs.metrics import DEFAULT_BUCKETS
    return DEFAULT_BUCKETS, counts


def _pct_from_buckets(bounds, counts, q):
    """Quantile estimate from (possibly diffed) bucket counts —
    linear inside the owning bucket, like Histogram.percentile."""
    total = sum(counts)
    if not total:
        return None
    target = q * total
    seen = 0.0
    for i, c in enumerate(counts):
        if seen + c >= target and c:
            lo = bounds[i - 1] if i else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1] * 2
            return round(lo + (hi - lo) * (target - seen) / c, 3)
        seen += c
    return round(bounds[-1] * 2, 3)


class _ServerLat:
    """Per-level server-side latency deltas: snapshot the
    ``serve.request_ms`` (admission→reply) and ``serve.batch.flush_ms``
    (device dispatch) histograms around a sweep level, report p50/p99
    of just that level's observations."""

    _FAMS = ("serve.request_ms", "serve.batch.flush_ms")

    def __init__(self):
        self._before = {f: _hist_counts(f) for f in self._FAMS}

    def delta(self):
        out = {}
        for fam, key in (("serve.request_ms", "request"),
                         ("serve.batch.flush_ms", "batch")):
            bounds, after = _hist_counts(fam)
            b_bounds, before = self._before[fam]
            if after is None:
                out[key] = None
                continue
            if before is None:
                diff = after
            else:
                diff = [a - b for a, b in zip(after, before)]
            out[key] = {
                "count": sum(diff),
                "p50_ms": _pct_from_buckets(bounds, diff, 0.50),
                "p99_ms": _pct_from_buckets(bounds, diff, 0.99),
            }
        return out


class _GenLat:
    """Per-level decode-path latency deltas: ``serve.gen.ttft_ms``
    (admission to first streamed token — prefill wait + dispatch) and
    ``serve.gen.step_ms`` (one packed decode step), reported as p50/p99
    of just this level's observations."""

    _FAMS = ("serve.gen.ttft_ms", "serve.gen.step_ms")

    def __init__(self):
        self._before = {f: _hist_counts(f) for f in self._FAMS}

    def delta(self):
        out = {}
        for fam, key in (("serve.gen.ttft_ms", "ttft"),
                         ("serve.gen.step_ms", "step")):
            bounds, after = _hist_counts(fam)
            _b, before = self._before[fam]
            if after is None:
                out[key] = None
                continue
            diff = after if before is None else \
                [a - b for a, b in zip(after, before)]
            out[key] = {"count": sum(diff),
                        "p50_ms": _pct_from_buckets(bounds, diff, 0.50),
                        "p99_ms": _pct_from_buckets(bounds, diff, 0.99)}
        return out


def _make_gen_checkpoint(tmpdir, vocab, dim, cache_len):
    """Save a tiny attention-LM GENERATE checkpoint (the KV-cache/pos
    contract of example/char_lm) — the sweep exercises the production
    from_checkpoint -> is_generative -> scheduler path."""
    import mxtpu as mx
    from mxtpu.model import save_checkpoint
    rng = np.random.RandomState(11)
    data = mx.sym.Variable("data")
    pos = mx.sym.Variable("pos", shape=(0,), dtype="int32")
    kc = mx.sym.Variable("kc", shape=(0, cache_len, dim))
    vc = mx.sym.Variable("vc", shape=(0, cache_len, dim))
    emb = mx.sym.Embedding(data=data, input_dim=vocab, output_dim=dim,
                           name="emb")
    q = mx.sym.FullyConnected(data=emb, num_hidden=dim, flatten=False,
                              name="q")
    k = mx.sym.FullyConnected(data=emb, num_hidden=dim, flatten=False,
                              name="k")
    v = mx.sym.FullyConnected(data=emb, num_hidden=dim, flatten=False,
                              name="v")
    att = mx.sym.cached_attention(q, k, v, kc, vc, pos, num_heads=2,
                                  name="att")
    out = mx.sym.FullyConnected(data=att[0], num_hidden=vocab,
                                flatten=False, name="proj")
    sym = mx.sym.Group([out, mx.sym.identity(att[1], name="kc_next"),
                        mx.sym.identity(att[2], name="vc_next")])
    f = lambda *s: rng.randn(*s).astype(np.float32) * 0.4  # noqa: E731
    args = {"emb_weight": f(vocab, dim),
            "q_weight": f(dim, dim), "q_bias": np.zeros(dim, "f"),
            "k_weight": f(dim, dim), "k_bias": np.zeros(dim, "f"),
            "v_weight": f(dim, dim), "v_bias": np.zeros(dim, "f"),
            "proj_weight": f(vocab, dim),
            "proj_bias": np.zeros(vocab, "f")}
    prefix = os.path.join(tmpdir, "bench_lm")
    save_checkpoint(prefix, 0, sym,
                    {n: mx.nd.array(a) for n, a in args.items()}, {})
    return prefix


def _run_generate_level(addr, n_clients, max_new, vocab):
    """One generate sweep level: n_clients threads, one greedy
    sequence each, streamed over the continuous scheduler. Tokens/s is
    end-to-end (admission to terminal verdict, prefill included);
    TTFT/step percentiles come from the registry histogram deltas."""
    from mxtpu.serving import ServingClient
    gen_lat = _GenLat()
    counts, errors = [0] * n_clients, [0]
    lock = threading.Lock()
    start = threading.Event()
    # every client finishes constructing BEFORE the clock starts —
    # otherwise connection setup of the tail threads is billed to the
    # measured window and tokens/s undershoots at mid concurrency
    ready = threading.Barrier(n_clients + 1)

    def one_client(j):
        cli = ServingClient(addrs=[addr])
        ready.wait(timeout=60.0)
        start.wait(timeout=30.0)
        try:
            toks, _info = cli.generate2(
                [1 + (j % (vocab - 2)), 2, 3], max_new=max_new,
                model="bench_lm")
            counts[j] = len(toks)
        except Exception:
            with lock:
                errors[0] += 1
        cli.close()

    threads = [threading.Thread(target=one_client, args=(j,),
                                daemon=True) for j in range(n_clients)]
    for t in threads:
        t.start()
    ready.wait(timeout=60.0)
    t0 = time.perf_counter()
    start.set()
    for t in threads:
        t.join(timeout=600)
    wall = time.perf_counter() - t0
    tokens = sum(counts)
    row = {"clients": n_clients, "sequences": n_clients - errors[0],
           "tokens": tokens, "errors": errors[0],
           "tok_s": round(tokens / wall, 1) if wall > 0 else 0.0}
    row.update(gen_lat.delta())
    return row


def _measure_generate(tmpdir, levels, max_new, vocab, dim, cache_len,
                      slots):
    """The continuous-batching sweep, on its own server so the predict
    sweep's batcher stats stay untouched."""
    from mxtpu.serving import InferenceEngine, ModelServer
    os.environ.setdefault("MXTPU_SERVE_GENERATE_SLOTS", str(slots))
    prefix = _make_gen_checkpoint(tmpdir, vocab, dim, cache_len)
    engine = InferenceEngine.from_checkpoint(
        prefix, 0, {"data": (1,)}, buckets=(1,))
    srv = ModelServer(engine, model_name="bench_lm").start()
    try:
        # warm sequence, then pin: the sweep must retrace NOTHING
        _run_generate_level(srv.address, 2, 4, vocab)
        compiles_after_warm = engine.cache.compiles
        rows = [_run_generate_level(srv.address, n, max_new, vocab)
                for n in levels]
        sched = srv.stats()["models"]["bench_lm"]["scheduler"]
        return {
            "slots": engine.generate_spec()["slots"],
            "max_new": max_new,
            "cache_len": cache_len,
            "levels": rows,
            # the batching win: top sweep level over the bottom one
            "speedup_top_vs_bottom": round(
                rows[-1]["tok_s"] / rows[0]["tok_s"], 2)
            if rows[0]["tok_s"] else None,
            "decode_steps": sched["steps"],
            "retraces_after_warmup":
                engine.cache.compiles - compiles_after_warm,
        }
    finally:
        srv.stop()


def _make_checkpoint(tmpdir, in_dim, hidden, classes):
    """Save a tiny-MLP Module checkpoint the replicas would load in
    production — the bench exercises the real from_checkpoint path."""
    import mxtpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (8, in_dim))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Uniform(0.1))
    prefix = os.path.join(tmpdir, "bench_model")
    mod.save_checkpoint(prefix, 0)
    return prefix


def _run_level(addr, n_clients, iters, in_dim, budget_ms):
    """One closed-loop sweep level: n_clients threads, each its own
    client/connection, iters predicts back to back. Client-side
    latency percentiles come from the raw sample list; server-side
    ``serve.request_ms`` / ``serve.batch.flush_ms`` percentiles come
    from the registry histograms' per-level bucket deltas — the same
    numbers a fleet poller (mxtop, the autoscaling controller) reads."""
    from mxtpu.serving import ServingClient, Overloaded, DeadlineExceeded
    srv_lat = _ServerLat()
    lat, sheds, expired, errors = [], [0], [0], [0]
    lock = threading.Lock()
    start = threading.Event()

    def one_client(seed):
        rng = np.random.RandomState(seed)
        cli = ServingClient(addrs=[addr], budget_ms=budget_ms)
        mine = []
        start.wait(timeout=30.0)
        for _ in range(iters):
            x = rng.rand(1, in_dim).astype("f")
            t0 = time.perf_counter()
            try:
                cli.predict(x)
                mine.append(time.perf_counter() - t0)
            except Overloaded:
                with lock:
                    sheds[0] += 1
            except DeadlineExceeded:
                with lock:
                    expired[0] += 1
            except (ConnectionError, RuntimeError):
                with lock:
                    errors[0] += 1
        cli.close()
        with lock:
            lat.extend(mine)

    threads = [threading.Thread(target=one_client, args=(i,), daemon=True)
               for i in range(n_clients)]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    start.set()
    for t in threads:
        t.join(timeout=600)
    wall = time.perf_counter() - t0
    attempts = n_clients * iters
    ok = len(lat)
    return {
        "clients": n_clients,
        "attempts": attempts,
        "answered": ok,
        "req_s": round(ok / wall, 1) if wall > 0 else 0.0,
        "p50_ms": round(_pct(lat, 0.50) * 1e3, 3) if lat else None,
        "p99_ms": round(_pct(lat, 0.99) * 1e3, 3) if lat else None,
        "shed": sheds[0],
        "shed_rate": round(sheds[0] / attempts, 4),
        "expired": expired[0],
        "errors": errors[0],
        # server-side histograms (bucket-delta estimates): request =
        # admission->reply, batch = device dispatch wall per flush
        "server_lat": srv_lat.delta(),
    }


def _measure_rollout(srv, engine, prefix, in_dim, swaps=5):
    """Swap latency + weight-staleness lag: how long one weight-version
    install takes (publish-to-serving handoff excluded), and how stale
    a poll-mode replica's weights run end to end — publish timestamp
    to the version actually answering requests (WeightPublisher →
    WeightSync at MXTPU_SERVE_WEIGHT_POLL → device_put swap). Both are
    the operational numbers of the continuous-deployment story
    (docs/serving.md "Rollout & weight streaming")."""
    from mxtpu.model import load_checkpoint
    from mxtpu.serving import WeightPublisher, WeightSync
    _sym, arg_params, _aux = load_checkpoint(prefix, 0)
    base = {n: v.asnumpy() for n, v in arg_params.items()}
    compiles_before = engine.cache.compiles
    swap_s = []
    for i in range(swaps):
        params = {n: v * (1.0 + 0.01 * (i + 1)) for n, v in base.items()}
        t0 = time.perf_counter()
        v = srv.swap_weights(params)
        swap_s.append(time.perf_counter() - t0)
        assert v is not None
    weight_dir = tempfile.mkdtemp(prefix="mxtpu_serve_bench_w_")
    pub = WeightPublisher(weight_dir)
    poll_s = 0.02
    sync = WeightSync(srv, weight_dir=weight_dir, poll=poll_s)
    sync.catch_up()
    sync.start()
    stale_s = []
    # versions must be PAST the engine's watermark (the direct swaps
    # above advanced it), or the lag would measure an instant no-op
    v0 = srv._engine.version_state()["latest"]
    for i in range(swaps):
        params = {n: v * (2.0 + 0.01 * i) for n, v in base.items()}
        out = pub.publish(params, version=v0 + i + 1)
        t0 = time.perf_counter()
        deadline = t0 + 30.0
        while time.perf_counter() < deadline:
            if srv._engine.version_state()["version"] >= out["version"]:
                break
            time.sleep(0.001)
        stale_s.append(time.perf_counter() - t0)
    sync.stop()
    return {
        "swaps": swaps,
        "swap_ms_p50": round(_pct(swap_s, 0.50) * 1e3, 3),
        "swap_ms_max": round(max(swap_s) * 1e3, 3),
        "poll_s": poll_s,
        "staleness_ms_p50": round(_pct(stale_s, 0.50) * 1e3, 3),
        "staleness_ms_max": round(max(stale_s) * 1e3, 3),
        "retraces": engine.cache.compiles - compiles_before,
    }


def run(clients_levels, iters, in_dim, hidden, classes, buckets,
        budget_ms, gen_levels=None, max_new=32, gen_dim=128,
        gen_cache=64, gen_slots=32):
    import mxtpu  # noqa: F401  (engine import path)
    from mxtpu import kvstore_async as ka
    from mxtpu.serving import InferenceEngine, ModelServer

    tmpdir = tempfile.mkdtemp(prefix="mxtpu_serve_bench_")
    prefix = _make_checkpoint(tmpdir, in_dim, hidden, classes)
    engine = InferenceEngine.from_checkpoint(
        prefix, 0, {"data": (in_dim,)}, buckets=buckets, warm=True)
    srv = ModelServer(engine, model_name="bench_mlp").start()
    local_saved = ka._LOCAL_ON
    try:
        # warmup pass, then pin the compile counter: the sweep must
        # post ZERO new compiles (per-request retraces)
        _run_level(srv.address, 2, 2, in_dim, budget_ms)
        compiles_after_warm = engine.cache.compiles

        levels = [_run_level(srv.address, n, iters, in_dim, budget_ms)
                  for n in clients_levels]
        # batching effectiveness, cumulative over the sweep
        b = srv.stats()["batcher"]
        mid = clients_levels[len(clients_levels) // 2]
        ka._LOCAL_ON = False
        tcp = _run_level(srv.address, mid, iters, in_dim, budget_ms)
        ka._LOCAL_ON = local_saved
        # the continuous-deployment numbers: swap latency + poll-mode
        # weight-staleness lag, with the zero-retrace pin riding along
        rollout = _measure_rollout(srv, engine, prefix, in_dim)
        # the continuous-batching generation sweep (ISSUE 17)
        generate = _measure_generate(
            tmpdir, gen_levels or clients_levels, max_new, 17,
            gen_dim, gen_cache, gen_slots)

        result = {
            "bench": "serving_loopback",
            "transport": "local" if local_saved else "tcp",
            "model": {"in_dim": in_dim, "hidden": hidden,
                      "classes": classes},
            "buckets": list(engine.buckets),
            "iters": iters,
            "budget_ms": budget_ms,
            "queue_depth": srv._depth,
            "batch_deadline_ms": srv._deadline_ms,
            "host_cores": os.cpu_count(),
            "levels": levels,
            "tcp": tcp,
            "batches": b["batches"],
            "batched_requests": b["batched_requests"],
            "avg_batch_rows": round(
                b["batched_rows"] / b["batches"], 2) if b["batches"]
            else 0.0,
            "max_batch_rows": b["max_batch_rows"],
            "rollout": rollout,
            "generate": generate,
            "retraces_after_warmup":
                engine.cache.compiles - compiles_after_warm,
        }
        return result
    finally:
        ka._LOCAL_ON = local_saved
        srv.stop()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", default=None,
                    help="comma list of concurrent-client sweep levels "
                         "(default 8,64,256; tiny mode 2,4)")
    ap.add_argument("--iters", type=int, default=None,
                    help="predicts per client per level (default 20; "
                         "tiny mode 3)")
    ap.add_argument("--budget-ms", type=float, default=2000.0)
    ap.add_argument("--in-dim", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--buckets", default="1,2,4,8,16,32")
    ap.add_argument("--max-new", type=int, default=None,
                    help="tokens per generated sequence (default 32; "
                         "tiny mode 8)")
    ap.add_argument("--no-write", action="store_true",
                    help="do not mirror the line to "
                         "docs/serving_bench.json")
    args = ap.parse_args()
    tiny = os.environ.get("MXTPU_BENCH_TINY", "0") != "0"
    clients = args.clients or ("2,4" if tiny else "8,64,256")
    iters = args.iters if args.iters is not None else (3 if tiny else 20)
    levels = [int(c) for c in clients.split(",") if c.strip()]

    max_new = args.max_new if args.max_new is not None else \
        (8 if tiny else 32)
    result = run(levels, iters, args.in_dim, args.hidden, args.classes,
                 args.buckets, args.budget_ms, gen_levels=levels,
                 max_new=max_new,
                 gen_dim=16 if tiny else 128,
                 gen_cache=16 if tiny else 64,
                 gen_slots=4 if tiny else 32)
    if tiny:
        result["tiny"] = True
    line = json.dumps(result)
    print(line, flush=True)
    if not args.no_write:
        with open(os.path.join(ROOT, "docs", "serving_bench.json"),
                  "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
