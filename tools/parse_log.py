#!/usr/bin/env python
"""Parse training logs into a table (reference tools/parse_log.py):
extracts per-epoch train/validation accuracy and throughput from the
``fit``/Speedometer log format.
"""
from __future__ import annotations

import argparse
import re
import sys


def parse(fname):
    tr_acc = {}
    va_acc = {}
    speed = {}
    with open(fname) as f:
        for line in f:
            m = re.search(r"Epoch\[(\d+)\].*Train-accuracy=([\d.]+)", line)
            if m:
                tr_acc[int(m.group(1))] = float(m.group(2))
            m = re.search(r"Epoch\[(\d+)\].*Validation-accuracy=([\d.]+)",
                          line)
            if m:
                va_acc[int(m.group(1))] = float(m.group(2))
            m = re.search(r"Epoch\[(\d+)\].*Speed: ([\d.]+) samples/sec",
                          line)
            if m:
                speed.setdefault(int(m.group(1)), []).append(
                    float(m.group(2)))
    return tr_acc, va_acc, speed


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("logfile")
    p.add_argument("--format", choices=("markdown", "none"),
                   default="markdown")
    args = p.parse_args()
    tr, va, sp = parse(args.logfile)
    epochs = sorted(set(tr) | set(va) | set(sp))
    if args.format == "markdown":
        print("| epoch | train-accuracy | valid-accuracy | speed |")
        print("| --- | --- | --- | --- |")
    for e in epochs:
        avg_speed = sum(sp.get(e, [0])) / max(len(sp.get(e, [1])), 1)
        print("| %d | %s | %s | %.1f |"
              % (e, tr.get(e, ""), va.get(e, ""), avg_speed))


if __name__ == "__main__":
    main()
