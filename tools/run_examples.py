#!/usr/bin/env python
"""Run every example end to end on the CPU mesh (the reference's example
suites double as integration tests; this is the local runner)."""
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXAMPLES = [
    ("image-classification/train_mnist.py", {}),
    ("image-classification/train_cifar10.py",
     {"ARGS": ["--synthetic", "48", "--num-layers", "8",
               "--batch-size", "8", "--num-epochs", "1",
               "--model-prefix", "ckpt/r8", "--data-nthreads", "2"]}),
    ("image-classification/benchmark_score.py",
     {"ARGS": ["--models", "resnet-50", "--batch-sizes", "1"]}),
    ("rnn/lstm_bucketing.py", {}),
    ("ssd/train_ssd_toy.py", {}),
    ("gan/dcgan_toy.py", {}),
    ("long-context/ring_attention_lm.py", {"DEVICES": 8}),
    ("model-parallel/tp_mlp.py", {"DEVICES": 8}),
    ("recommenders/matrix_fact.py", {}),
    ("sparse/linear_classification.py", {}),
    ("dlrm_click/dlrm_click.py", {}),
    ("char_lm/char_lm.py", {}),
    ("moe_transformer/moe_transformer.py", {"DEVICES": 8}),
    ("autoencoder/mnist_sae.py", {}),
    ("adversary/fgsm_mnist.py", {}),
    ("svm_mnist/svm_mnist.py", {}),
    ("multi-task/multitask_mnist.py", {}),
    ("vae/vae_mnist.py", {}),
    ("numpy-ops/custom_softmax.py", {}),
    ("bi-lstm-sort/sort_lstm.py", {}),
    ("cnn_text_classification/text_cnn.py", {}),
    ("nce-loss/nce_lm.py", {}),
    ("deep-embedded-clustering/dec_toy.py", {}),
    ("stochastic-depth/sd_resnet.py", {}),
    ("bayesian-methods/bbb_toy.py", {}),
    ("capsnet/capsnet_toy.py", {}),
    ("ctc/ctc_toy.py", {}),
    ("multivariate_time_series/lstnet_toy.py", {}),
    ("profiler/profile_resnet.py", {}),
    ("rcnn/train_rcnn_toy.py", {}),
    ("fcn-xs/fcn_toy.py", {}),
    ("speech_recognition/deepspeech_toy.py", {}),
    ("neural-style/neural_style_toy.py", {}),
    ("reinforcement-learning/dqn_toy.py", {}),
    ("captcha/captcha_toy.py", {}),
    ("dsd/dsd_toy.py", {}),
    ("gluon/mnist.py", {}),
    ("gluon/kaggle_k_fold_cross_validation.py", {}),
    ("gluon/lstm_crf.py", {}),
    ("gluon/actor_critic.py", {}),
    ("gluon/super_resolution.py", {}),
    ("gluon/word_language_model.py", {}),
    ("gluon/learning_rate_manipulation.py", {}),
    ("module/mnist_mlp.py", {}),
    ("module/python_loss.py", {}),
    ("module/sequential_module.py", {}),
    ("rnn-time-major/rnn_cell_demo.py", {}),
    ("memcost/inception_memcost.py", {}),
    ("cnn_chinese_text_classification/text_cnn.py", {}),
    ("kaggle-ndsb1/train_dsb.py", {}),
    ("kaggle-ndsb2/train_ndsb2.py", {}),
    ("utils/get_data.py", {}),
    ("python-howto/data_iter.py", {}),
    ("python-howto/multiple_outputs.py", {}),
    ("python-howto/monitor_weights.py", {}),
    ("mxnet_adversarial_vae/avae_toy.py", {}),
]


def main():
    failures = []
    for rel, cfg in EXAMPLES:
        path = os.path.join(ROOT, "example", rel)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        if cfg.get("DEVICES"):
            env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=%d"
                                % cfg["DEVICES"])
        else:
            env.pop("XLA_FLAGS", None)
        t0 = time.time()
        try:
            res = subprocess.run([sys.executable, path]
                                 + cfg.get("ARGS", []), env=env,
                                 capture_output=True, text=True,
                                 timeout=1200)
            rc, out = res.returncode, res.stdout[-800:] + res.stderr[-800:]
        except subprocess.TimeoutExpired as e:
            rc = -1
            out = ("TIMEOUT after 1200s\n" + str(e.stdout or "")[-800:]
                   + str(e.stderr or "")[-800:])
        status = "OK " if rc == 0 else "FAIL"
        print("%s %-45s %6.1fs" % (status, rel, time.time() - t0))
        if rc != 0:
            failures.append((rel, out))
    for rel, out in failures:
        print("\n--- %s ---\n%s" % (rel, out))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
