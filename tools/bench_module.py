#!/usr/bin/env python
"""Loopback microbench for the Module fused train step (ISSUE 5).

Measures steady-state ``Module.fit`` throughput — the exact hot loop
``fit`` runs per batch (``forward_backward`` → ``update`` →
``update_metric``) — for the two bundled CPU-runnable models:

* **mlp**  — 256→128→64→10 softmax MLP at batch 64
* **lenet** — LeNet-style conv/pool/conv/pool/fc on 1x28x28 at batch 4

Batch sizes are per-model: the fused step removes PER-STEP dispatch
overhead (python updater loop, per-batch metric sync, extra program
launches), so each model runs in the regime where the Module path — not
raw conv arithmetic on this 1-core CI host — is what's being measured:
the MLP is overhead-dominated even at batch 64; the conv net only below
~batch 8 (at batch 32+ its conv FLOPs bound a single core and the fused
win shrinks to ~1.2x — the full scan is in docs/perf_analysis.md).

Each model runs twice: ``MXTPU_MODULE_FUSED=1`` (one donated XLA program
per step: forward + backward + whole optimizer update + device-side
metric accumulation) and ``=0`` (the eager path: speculated fwd+bwd
program, per-parameter Python optimizer dispatches, per-batch
``asnumpy()`` metric sync). The warmup batches (compiles + metric
registration) are excluded; the metric is drained once at the end so the
async path's deferred work is counted.

``--dist`` (ISSUE 10) switches to the loopback-PS fit microbench: the
same hot loop driven through ``kvstore='dist_async'`` (in-process
server, local transport), measured three ways — the eager dist path
(per-param push/pull loop), the fused-dist SYNC mode (one grad-emitting
program + one coalesced push + one pull per batch, bit-for-bit with
eager) and the fused-dist ASYNC mode (push+pull pipelined on the
store's pool under the bounded-inflight window).

``--amp`` (ISSUE 12) sweeps mixed precision: the fp32 fused path vs
``MXTPU_AMP=bf16`` — single-host fit throughput, plus the dist sync
loop over REAL wire framing with pushpull bytes/step (bf16 frames
carry the dtype in the payload; the half-width-wire contract is
bytes ratio <= 0.55, also pinned structurally by
``ci/check_module_perf.py --amp``).

``--mesh`` (ISSUE 20) sweeps the pjit-sharded fused step: the fused
single-device fit loop vs the same loop compiled as an SPMD program
over an 8-way emulated mesh (``Module.set_sharding``), plus single vs
sharded AOT serving (``InferenceEngine(mesh=...)``) request rates. On
emulated CPU devices the mesh legs pay partitioning overhead instead
of banking real-chip speedup, so the row carries the structural
evidence alongside the rates: per-device store bytes (~1/N of total)
and a zero-recompile steady serve state (the hard pins live in
``ci/check_mesh_perf.py``).

Prints exactly ONE JSON line (tests/test_bench_contract.py parses it)
and mirrors it to docs/module_bench.json unless --no-write (the file
keeps one line per bench kind: ``module_fit``, ``module_fit_dist``,
``module_fit_amp`` and ``module_fit_mesh``). CPU-only.
MXTPU_BENCH_TINY shrinks the models/batch counts for the contract
test.

Run: JAX_PLATFORMS=cpu python tools/bench_module.py [--dist|--amp]
     [--batches 100]
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       JAX_PLATFORMS=cpu python tools/bench_module.py --mesh
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, ROOT)

TINY = os.environ.get("MXTPU_BENCH_TINY", "0") not in ("", "0")


def _mlp(mx, hidden=(128, 64), classes=10):
    net = mx.sym.var("data")
    for i, h in enumerate(hidden):
        net = mx.sym.FullyConnected(net, num_hidden=h, name="fc%d" % i)
        net = mx.sym.Activation(net, act_type="relu", name="act%d" % i)
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc_out")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _lenet(mx, classes=10):
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(5, 5), num_filter=4,
                             name="conv1")
    net = mx.sym.Activation(net, act_type="tanh", name="tanh1")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="pool1")
    net = mx.sym.Convolution(net, kernel=(5, 5), num_filter=8,
                             name="conv2")
    net = mx.sym.Activation(net, act_type="tanh", name="tanh2")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="pool2")
    net = mx.sym.Flatten(net, name="flat")
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="tanh", name="tanh3")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _data(model, n, batch_size):
    rng = np.random.RandomState(0)
    if model == "mlp":
        x = rng.randn(n, 256).astype("float32")
    else:
        x = rng.randn(n, 1, 28, 28).astype("float32")
    y = rng.randint(0, 10, n).astype("float32")
    return x, y


def _steady_state_rate(mx, sym, x, y, batch_size, batches, warmup,
                       mesh=None):
    """img/sec of the fit() hot loop after warmup, current env."""
    it = mx.io.NDArrayIter(x, y, batch_size=batch_size,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym, context=mx.cpu())
    if mesh is not None:
        mod.set_sharding(mesh)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "momentum": 0.9})
    metric = mx.metric.create("acc")
    pool = list(it)

    def one(batch):
        mod.forward_backward(batch)
        mod.update()
        mod.update_metric(metric, batch.label)

    for i in range(warmup):
        one(pool[i % len(pool)])
    metric.get()   # mxlint: allow(blocking-call) — drain any device accumulation; a value getter, not a wait
    metric.reset()

    t0 = time.perf_counter()
    for i in range(batches):
        one(pool[i % len(pool)])
    metric.get()   # mxlint: allow(blocking-call) — epoch-end read (value getter), both paths
    # flush async dispatch: the step's outputs must actually exist
    mod._exec_group.execs[0].arg_dict[
        mod._exec_group.param_names[0]].wait_to_read()
    dt = time.perf_counter() - t0
    fused = mod._fused is not None
    return batch_size * batches / dt, fused


DEFAULT_BS = {"mlp": 8, "lenet": 2} if TINY else {"mlp": 64, "lenet": 4}


def _dist_rate(mx, sym, x, y, batch_size, batches, warmup):
    """img/sec of the fit() hot loop against an in-process dist_async
    parameter service, current env (MXTPU_MODULE_FUSED[_DIST] /
    MXTPU_MODULE_DIST_MODE select the path)."""
    it = mx.io.NDArrayIter(x, y, batch_size=batch_size,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore="dist_async", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01})
    metric = mx.metric.create("acc")
    pool = list(it)

    def one(batch):
        mod.forward_backward(batch)
        mod.update()
        mod.update_metric(metric, batch.label)

    try:
        for i in range(warmup):
            one(pool[i % len(pool)])
        if mod._fused is not None:
            mod._fused.flush()
        metric.get()   # mxlint: allow(blocking-call) — drain any device accumulation; a value getter, not a wait
        metric.reset()

        t0 = time.perf_counter()
        for i in range(batches):
            one(pool[i % len(pool)])
        if mod._fused is not None:
            mod._fused.flush()   # outstanding async windows count
        metric.get()   # mxlint: allow(blocking-call) — epoch-end read (value getter), both paths
        mod._exec_group.execs[0].arg_dict[
            mod._exec_group.param_names[0]].wait_to_read()
        dt = time.perf_counter() - t0
        fused = mod._fused is not None
    finally:
        mod._kvstore.close()
    return batch_size * batches / dt, fused


def run_dist(batches, warmup, batch_size=None):
    """The --dist sweep: eager vs fused-sync vs fused-async, loopback
    PS, mlp model (the dispatch-bound regime the dist fast path
    targets)."""
    import mxtpu as mx

    os.environ.setdefault("MXTPU_PS_HEARTBEAT", "0")
    bs = batch_size or DEFAULT_BS["mlp"]
    n = max(4 * bs, 64)
    x, y = _data("mlp", n, bs)
    sym = _mlp(mx)
    saved = {k: os.environ.get(k) for k in
             ("MXTPU_MODULE_FUSED", "MXTPU_MODULE_FUSED_DIST",
              "MXTPU_MODULE_DIST_MODE")}
    rates = {}
    try:
        for name, env in (
                ("eager", {"MXTPU_MODULE_FUSED": "1",
                           "MXTPU_MODULE_FUSED_DIST": "0"}),
                ("fused_sync", {"MXTPU_MODULE_FUSED": "1",
                                "MXTPU_MODULE_FUSED_DIST": "1",
                                "MXTPU_MODULE_DIST_MODE": "sync"}),
                ("fused_async", {"MXTPU_MODULE_FUSED": "1",
                                 "MXTPU_MODULE_FUSED_DIST": "1",
                                 "MXTPU_MODULE_DIST_MODE": "async"})):
            os.environ.update(env)
            rate, fused = _dist_rate(mx, sym, x, y, bs, batches, warmup)
            assert fused == (name != "eager"), \
                "%s path engagement mismatch" % name
            rates[name] = rate
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    row = {"batch_size": bs,
           "eager_img_s": round(rates["eager"], 1),
           "fused_sync_img_s": round(rates["fused_sync"], 1),
           "fused_async_img_s": round(rates["fused_async"], 1),
           "speedup_sync": round(rates["fused_sync"] / rates["eager"], 2),
           "speedup_async": round(rates["fused_async"] / rates["eager"],
                                  2)}
    return {"bench": "module_fit_dist", "tiny": TINY,
            "batches": batches, "warmup": warmup,
            "host_cores": os.cpu_count(), "models": {"mlp": row}}


def _amp_dist_rate(mx, sym, x, y, batch_size, batches, warmup):
    """img/sec + wire bytes/step of the fused-sync dist fit hot loop
    over the REAL framing (local transport off so the byte counters
    tick), current MXTPU_AMP env."""
    from mxtpu import kvstore_async as ka
    it = mx.io.NDArrayIter(x, y, batch_size=batch_size,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    saved_local = ka._LOCAL_ON
    ka._LOCAL_ON = False
    try:
        mod.init_optimizer(kvstore="dist_async", optimizer="sgd",
                           optimizer_params={"learning_rate": 0.01})
        kv = mod._kvstore
        pool = list(it)

        def one(batch):
            mod.forward_backward(batch)
            mod.update()

        for i in range(warmup):
            one(pool[i % len(pool)])
        mod._fused.flush()
        before = kv._stats.snapshot()
        t0 = time.perf_counter()
        for i in range(batches):
            one(pool[i % len(pool)])
        mod._fused.flush()
        mod._exec_group.execs[0].arg_dict[
            mod._exec_group.param_names[0]].wait_to_read()
        dt = time.perf_counter() - t0
        after = kv._stats.snapshot()
        sent = (after["bytes_sent"] - before["bytes_sent"]) / batches
        recv = (after["bytes_recv"] - before["bytes_recv"]) / batches
        assert mod._fused is not None and mod._fused.mode == "dist"
        kv.close()
    finally:
        ka._LOCAL_ON = saved_local
    return batch_size * batches / dt, sent, recv


def run_amp(batches, warmup, batch_size=None):
    """The --amp sweep (ISSUE 12): fp32 fused vs bf16 fused, single-host
    AND dist sync over the wire — throughput plus pushpull bytes/step
    (the <= 0.55x half-width-wire contract ci/check_module_perf.py
    --amp pins structurally)."""
    import mxtpu as mx

    os.environ.setdefault("MXTPU_PS_HEARTBEAT", "0")
    bs = batch_size or DEFAULT_BS["mlp"]
    # dist leg runs the wire-bound regime (small batch: compute per
    # step shrinks, the ~335KB/step pushpull stays) — that is where
    # the half-width wire pays on a CPU host whose bf16 matmuls are
    # EMULATED; on real hardware bf16 also wins the compute leg
    dist_bs = batch_size or (DEFAULT_BS["mlp"] if TINY else 16)
    sym = _mlp(mx)
    saved = {k: os.environ.get(k) for k in
             ("MXTPU_AMP", "MXTPU_MODULE_FUSED", "MXTPU_MODULE_FUSED_DIST",
              "MXTPU_MODULE_DIST_MODE")}
    os.environ.update({"MXTPU_MODULE_FUSED": "1",
                       "MXTPU_MODULE_FUSED_DIST": "1",
                       "MXTPU_MODULE_DIST_MODE": "sync"})
    local, dist = {}, {}
    try:
        for name in ("fp32", "bf16"):
            os.environ["MXTPU_AMP"] = "" if name == "fp32" else "bf16"
            x, y = _data("mlp", max(4 * bs, 64), bs)
            rate, fused = _steady_state_rate(mx, sym, x, y, bs, batches,
                                             warmup)
            assert fused, "%s local path did not engage" % name
            local[name] = rate
            xd, yd = _data("mlp", max(4 * dist_bs, 64), dist_bs)
            dist[name] = _amp_dist_rate(mx, sym, xd, yd, dist_bs,
                                        batches, warmup)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    wire_ratio = (dist["bf16"][1] + dist["bf16"][2]) / max(
        1.0, dist["fp32"][1] + dist["fp32"][2])
    return {"bench": "module_fit_amp", "tiny": TINY,
            "batches": batches, "warmup": warmup,
            "host_cores": os.cpu_count(),
            "models": {"mlp": {
                "batch_size": bs,
                "fp32_img_s": round(local["fp32"], 1),
                "bf16_img_s": round(local["bf16"], 1),
                "speedup": round(local["bf16"] / local["fp32"], 2)}},
            "dist": {
                "batch_size": dist_bs,
                "fp32_img_s": round(dist["fp32"][0], 1),
                "bf16_img_s": round(dist["bf16"][0], 1),
                "speedup": round(dist["bf16"][0] / dist["fp32"][0], 2),
                "fp32_bytes_per_step": round(dist["fp32"][1]
                                             + dist["fp32"][2]),
                "bf16_bytes_per_step": round(dist["bf16"][1]
                                             + dist["bf16"][2]),
                "wire_bytes_ratio": round(wire_ratio, 3)}}


def _mesh_store_stats(mx, jax, sym, x, y, batch_size, mesh):
    """One mesh-mode train step, then the structural numbers the row
    carries: host params for the serve leg + the donated store's
    (total, worst-per-device, devices-occupied) byte split across
    params AND optimizer-state leaves."""
    it = mx.io.NDArrayIter(x, y, batch_size=batch_size,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.set_sharding(mesh)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "momentum": 0.9})
    batch = next(iter(it))
    mod.forward_backward(batch)
    mod.update()
    leaves = [a._data for a in mod._fused._group.param_store.values()]
    for state in getattr(mod._updater, "states", {}).values():
        for leaf in jax.tree_util.tree_leaves(state):
            leaf = getattr(leaf, "_data", leaf)
            if hasattr(leaf, "addressable_shards"):
                leaves.append(leaf)
    per_dev, total = {}, 0
    for arr in leaves:
        total += arr.nbytes
        for s in arr.addressable_shards:
            per_dev[s.device.id] = per_dev.get(s.device.id, 0) \
                + s.data.nbytes
    args_, _ = mod.get_params()
    host = {k: v.asnumpy() for k, v in args_.items()}
    return host, total, max(per_dev.values()), len(per_dev)


def _serve_rate(mx, sym, host, batches, mesh=None):
    """req/sec of the AOT predict menu on repeat batch-8 requests,
    plus the recompile count across the timed window (must be 0)."""
    from mxtpu.serving import InferenceEngine
    eng = InferenceEngine(sym, host, {}, {"data": (256,)},
                          buckets=(8,), warm=True, mesh=mesh)
    q = np.random.RandomState(1).randn(8, 256).astype("float32")
    eng.predict([q])                      # any residual placement work
    before = eng.stats()["compiles"]
    t0 = time.perf_counter()
    for _ in range(batches):
        eng.predict([q])
    dt = time.perf_counter() - t0
    return batches / dt, eng.stats()["compiles"] - before


def run_mesh(batches, warmup, batch_size=None):
    """The --mesh sweep (ISSUE 20): fused single-device vs pjit-sharded
    fused train loop, and single vs sharded serving, on the emulated
    8-way mesh. Every param dim 0 divides the mesh so the FSDP default
    rule shards the whole store."""
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("MXTPU_PS_HEARTBEAT", "0")
    import jax
    import mxtpu as mx
    from mxtpu.parallel import MeshContext

    n_dev = len(jax.devices())
    mesh = MeshContext({"model": n_dev})
    hidden = (64, 32) if TINY else (256, 64)
    sym = _mlp(mx, hidden=hidden, classes=8)
    bs = batch_size or DEFAULT_BS["mlp"]
    n = max(4 * bs, 64)
    rng = np.random.RandomState(0)
    x = rng.randn(n, 256).astype("float32")
    y = rng.randint(0, 8, n).astype("float32")

    saved = {k: os.environ.get(k)
             for k in ("MXTPU_MODULE_FUSED", "MXTPU_MESH")}
    os.environ.pop("MXTPU_MESH", None)     # explicit mesh only: the
    os.environ["MXTPU_MODULE_FUSED"] = "1"  # single leg must stay single
    try:
        single_rate, f1 = _steady_state_rate(mx, sym, x, y, bs,
                                             batches, warmup)
        mesh_rate, f2 = _steady_state_rate(mx, sym, x, y, bs,
                                           batches, warmup, mesh=mesh)
        assert f1 and f2, "fused path did not engage"
        host, store_total, store_worst, store_devs = _mesh_store_stats(
            mx, jax, sym, x, y, bs, mesh)
        serve_single, rc0 = _serve_rate(mx, sym, host, batches)
        serve_mesh, rc1 = _serve_rate(mx, sym, host, batches, mesh=mesh)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {"bench": "module_fit_mesh", "tiny": TINY,
            "batches": batches, "warmup": warmup,
            "host_cores": os.cpu_count(), "devices": n_dev,
            "train": {
                "batch_size": bs,
                "fused_img_s": round(single_rate, 1),
                "mesh_img_s": round(mesh_rate, 1),
                "mesh_vs_single": round(mesh_rate / single_rate, 2),
                "store_bytes": store_total,
                "store_bytes_worst_device": store_worst,
                "store_devices": store_devs},
            "serve": {
                "batch_size": 8,
                "single_req_s": round(serve_single, 1),
                "mesh_req_s": round(serve_mesh, 1),
                "mesh_vs_single": round(serve_mesh / serve_single, 2),
                "recompiles": rc0 + rc1}}


def run(batches, warmup, batch_size=None):
    import mxtpu as mx

    models = {}
    for name, sym_fn in (("mlp", _mlp), ("lenet", _lenet)):
        bs = batch_size or DEFAULT_BS[name]
        n = max(4 * bs, 64)
        x, y = _data(name, n, bs)
        sym = sym_fn(mx)
        saved = os.environ.get("MXTPU_MODULE_FUSED")
        try:
            os.environ["MXTPU_MODULE_FUSED"] = "1"
            fused_rate, was_fused = _steady_state_rate(
                mx, sym, x, y, bs, batches, warmup)
            assert was_fused, "fused path did not engage"
            os.environ["MXTPU_MODULE_FUSED"] = "0"
            eager_rate, was_fused = _steady_state_rate(
                mx, sym, x, y, bs, batches, warmup)
            assert not was_fused
        finally:
            if saved is None:
                os.environ.pop("MXTPU_MODULE_FUSED", None)
            else:
                os.environ["MXTPU_MODULE_FUSED"] = saved
        models[name] = {"batch_size": bs,
                        "fused_img_s": round(fused_rate, 1),
                        "eager_img_s": round(eager_rate, 1),
                        "speedup": round(fused_rate / eager_rate, 2)}
    return {"bench": "module_fit", "tiny": TINY,
            "batches": batches, "warmup": warmup,
            "host_cores": os.cpu_count(), "models": models}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=4 if TINY else 100,
                    help="steady-state batches per timing run")
    ap.add_argument("--warmup", type=int, default=2 if TINY else 8)
    ap.add_argument("--batch-size", type=int, default=None,
                    help="override the per-model defaults (%r)"
                    % (DEFAULT_BS,))
    ap.add_argument("--dist", action="store_true",
                    help="loopback-PS fit microbench: eager vs fused "
                         "sync vs fused async over kvstore='dist_async'")
    ap.add_argument("--amp", action="store_true",
                    help="mixed-precision microbench: fp32 vs bf16 fused "
                         "(single-host + dist sync over the wire, with "
                         "pushpull bytes/step)")
    ap.add_argument("--mesh", action="store_true",
                    help="pjit-sharded microbench: fused single-device "
                         "vs 8-way mesh train + serve (set XLA_FLAGS="
                         "--xla_force_host_platform_device_count=8)")
    ap.add_argument("--no-write", action="store_true",
                    help="do not mirror the line to docs/module_bench.json")
    args = ap.parse_args()

    if args.mesh:
        result = run_mesh(args.batches, args.warmup, args.batch_size)
    elif args.amp:
        result = run_amp(args.batches, args.warmup, args.batch_size)
    elif args.dist:
        result = run_dist(args.batches, args.warmup, args.batch_size)
    else:
        result = run(args.batches, args.warmup, args.batch_size)
    line = json.dumps(result)
    print(line, flush=True)
    if not args.no_write:
        # the file keeps one line per bench kind (module_fit,
        # module_fit_dist, module_fit_amp, module_fit_mesh): replace
        # this kind's line, keep the others
        path = os.path.join(ROOT, "docs", "module_bench.json")
        kept = []
        if os.path.exists(path):
            with open(path) as f:
                for existing in f:
                    existing = existing.strip()
                    if not existing:
                        continue
                    try:
                        if json.loads(existing).get("bench") == \
                                result["bench"]:
                            continue
                    except ValueError:
                        continue
                    kept.append(existing)
        with open(path, "w") as f:
            for existing in kept:
                f.write(existing + "\n")
            f.write(line + "\n")


if __name__ == "__main__":
    main()
