#!/usr/bin/env python
"""Measure dist_async parameter-service push/pull throughput at realistic
parameter volume (reference scale: ResNet-50 is ~25.5M fp32 params ≈
102 MB/step each way).

Round-4 verdict finding: each push shipped the full dense gradient as one
pickled frame through one socket — correctness was proven but throughput
at real sizes was unmeasured. This tool measures it, across the levers
that changed in round 5:

* part splitting (MXTPU_KVSTORE_BIGARRAY_BOUND row chunks, reference
  BIGARRAY_BOUND splits) — parts move concurrently over the worker pool;
* server count (parts of one array spread over servers);
* 2-bit wire compression (16x payload cut, worker-side residual).

Writes docs/ps_throughput.json and prints it. CPU-only — no TPU needed,
so this evidence lands every round regardless of the relay.

Run: JAX_PLATFORMS=cpu python tools/bench_ps.py [--mb 100] [--iters 5]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, ROOT)


def measure(n_servers, bound, compress, total_mb, iters):
    """Time init+push+pull of a ResNet-50-shaped parameter set; returns
    MB/s for push and pull (payload MB counted pre-compression — the
    useful-gradient rate, matching how the reference reports it)."""
    import mxtpu as mx
    from mxtpu import kvstore_async as ka

    servers = [ka.ParameterServer().start() for _ in range(n_servers)]
    saved = {k: os.environ.get(k) for k in ("MXTPU_PS_ADDRS",)}
    os.environ["MXTPU_PS_ADDRS"] = ",".join(s.address for s in servers)
    old_bound = ka._BIGARRAY_BOUND
    ka._BIGARRAY_BOUND = bound
    try:
        kv = mx.kv.create("dist_async")
        if compress:
            kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        # ResNet-50-ish split: one fat fc-like matrix plus conv-sized
        # blocks, padded to the requested volume
        total_elems = int(total_mb * 1e6 / 4)
        shapes = [(2048, 1000)]
        left = total_elems - 2048 * 1000
        while left > 0:
            n = min(left, 2359296)   # a 3x3x512x512 conv worth
            rows = max(1, n // 4608)
            shapes.append((rows, 4608))
            left -= rows * 4608
        arrs = [mx.nd.array(np.random.RandomState(i).rand(*s)
                            .astype("f")) for i, s in enumerate(shapes)]
        outs = [mx.nd.zeros(s) for s in shapes]
        for i, a in enumerate(arrs):
            kv.init(i, a)
        payload_mb = sum(a.size for a in arrs) * 4 / 1e6

        t0 = time.perf_counter()
        for _ in range(iters):
            for i, a in enumerate(arrs):
                kv.push(i, a)
        push_s = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            for i, o in enumerate(outs):
                kv.pull(i, out=o)
        pull_s = (time.perf_counter() - t0) / iters
        n_parts = sum(len(p) for p in kv._parts.values())
        kv.close()
        return {"payload_mb": round(payload_mb, 1),
                "n_parts": n_parts,
                "push_mb_s": round(payload_mb / push_s, 1),
                "pull_mb_s": round(payload_mb / pull_s, 1),
                "push_s": round(push_s, 3), "pull_s": round(pull_s, 3)}
    finally:
        ka._BIGARRAY_BOUND = old_bound
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for s in servers:
            s.stop()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=float, default=100.0,
                    help="parameter volume (ResNet-50 fp32 ~= 102)")
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()

    grid = [
        # label, n_servers, bound(elems), compress
        ("1srv_whole", 1, 1 << 62, False),   # round-4 behavior
        ("1srv_parts", 1, 1000000, False),
        ("2srv_parts", 2, 1000000, False),
        ("4srv_parts", 4, 1000000, False),
        ("1srv_parts_2bit", 1, 1000000, True),
        ("2srv_parts_2bit", 2, 1000000, True),
    ]
    report = {"volume_mb": args.mb, "iters": args.iters,
              "host_cores": os.cpu_count(), "timestamp":
              time.strftime("%F %T")}
    for label, n_srv, bound, comp in grid:
        report[label] = measure(n_srv, bound, comp, args.mb, args.iters)
        print(label, report[label], flush=True)
    out = os.path.join(ROOT, "docs", "ps_throughput.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print("wrote", out)


if __name__ == "__main__":
    main()
