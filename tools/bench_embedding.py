#!/usr/bin/env python
"""Loopback microbench for the row-sparse embedding wire (ISSUE 13).

Sweeps table size x rows-touched-per-step and measures, over REAL wire
framing (the TCP loopback transport — byte counters need frames), what
the sparse fast path exists to change:

* **bytes/step** — dense ``push_pull`` ships the whole table both
  ways; ``sparse_push_pull`` ships ``(row_ids, rows)`` and gets the
  same rows back. The ratio must track rows-touched / table-rows, not
  table size.
* **steps/s** — the server applies row-wise
  (``Optimizer.update_host_rows``: only touched rows pay optimizer
  cost) vs the dense full-table apply.

Prints exactly ONE JSON line (tests/test_bench_contract.py parses it)
and mirrors it to docs/embedding_bench.json unless --no-write.
CPU-only; MXTPU_BENCH_TINY shrinks the sweep for the contract test.

Run: JAX_PLATFORMS=cpu python tools/bench_embedding.py [--steps 30]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXTPU_PS_HEARTBEAT", "0")
os.environ["MXTPU_PS_LOCAL"] = "0"   # bytes need real framing

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np                                    # noqa: E402

import mxtpu as mx                                    # noqa: E402


def _step_stats(kv, fn, steps):
    before = kv.stats()
    t0 = time.perf_counter()
    for _ in range(steps):
        fn()
    dt = time.perf_counter() - t0
    after = kv.stats()
    wire = (after["bytes_sent"] - before["bytes_sent"]
            + after["bytes_recv"] - before["bytes_recv"])
    return wire / steps, steps / dt


def run_point(rows, dim, touched, steps, optimizer):
    """One (table size, rows touched) point: dense vs sparse, fresh
    stores so optimizer state never leaks across measurements."""
    r = np.random.RandomState(0)
    ids = np.sort(r.choice(rows, size=touched, replace=False)
                  ).astype("int64")
    g_rows = r.rand(touched, dim).astype("f")
    g_dense = np.zeros((rows, dim), "f")
    g_dense[ids] = g_rows
    out = {}
    for kind in ("dense", "sparse"):
        kv = mx.kv.create("dist_async")
        try:
            kv.init("emb", mx.nd.zeros((rows, dim)))
            kv.set_optimizer(mx.optimizer.create(
                optimizer, learning_rate=0.1, rescale_grad=1.0))
            tgt = mx.nd.zeros((rows, dim))
            if kind == "dense":
                fn = lambda: kv.push_pull("emb", g_dense, out=tgt)  # noqa: E731
            else:
                fn = lambda: kv.sparse_push_pull(                   # noqa: E731
                    "emb", ids, g_rows, out=tgt)
            fn()                       # warmup (plan + state slots)
            bytes_step, steps_s = _step_stats(kv, fn, steps)
            out[kind] = {"bytes_per_step": round(bytes_step, 1),
                         "steps_per_s": round(steps_s, 2)}
        finally:
            kv.close()
    out["bytes_ratio"] = round(
        out["sparse"]["bytes_per_step"]
        / max(1.0, out["dense"]["bytes_per_step"]), 5)
    out["touch_fraction"] = round(touched / rows, 5)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--optimizer", default="sgd")
    ap.add_argument("--no-write", action="store_true",
                    help="do not mirror the line to "
                         "docs/embedding_bench.json")
    args = ap.parse_args()

    tiny = bool(os.environ.get("MXTPU_BENCH_TINY"))
    steps = 4 if tiny else args.steps
    if tiny:
        sweep = [(1000, 16, 10)]
    else:
        # table rows x dim x rows-touched-per-step: 1% and 10% touch
        # at two table sizes (the 1%-touch row is the CI contract)
        sweep = [(10000, 32, 100), (10000, 32, 1000),
                 (100000, 16, 1000), (100000, 16, 10000)]

    points = []
    for rows, dim, touched in sweep:
        pt = run_point(rows, dim, touched, steps, args.optimizer)
        pt.update(rows=rows, dim=dim, touched=touched)
        points.append(pt)

    result = {"bench": "embedding_sparse_wire",
              "optimizer": args.optimizer,
              "steps": steps,
              "transport": "tcp",
              "points": points}
    line = json.dumps(result)
    print(line)
    if not args.no_write:
        with open(os.path.join(ROOT, "docs", "embedding_bench.json"),
                  "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
