#!/usr/bin/env python
"""Streaming data-plane bench (ISSUE 18): the durable-log and
exactly-once loop costs the continual-learning path pays.

Three sections (numbers land in docs/perf_analysis.md "Streaming"):

* **append** — StreamWriter records/s and MB/s at the default segment
  size, plus the fsync-per-append rate (``MXTPU_STREAM_FSYNC=1``): the
  price of per-record durability vs the default seal-time durability.
* **tail** — StreamReader records/s over sealed segments (the cold
  respawn catch-up read), CRC verification included.
* **loop** — the exactly-once serve→train handshake over a loopback
  ParameterServer: stream_push frames/s with the offset commit riding
  each frame (records/s = frames/s x batch), and the replay-refusal
  rate (a respawn storm's worst case: every frame a dup — refusal must
  be CHEAPER than an apply, or crash recovery melts the server).

Prints exactly ONE JSON line (tests/test_bench_contract.py parses it)
and mirrors it to docs/streaming_bench.json unless --no-write.
CPU-only; MXTPU_BENCH_TINY=1 shrinks counts for the contract test.

Run: JAX_PLATFORMS=cpu python tools/bench_streaming.py [--records N]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MXTPU_PS_HEARTBEAT"] = "0"

TINY = os.environ.get("MXTPU_BENCH_TINY") == "1"


def bench_append(root, n, payload, fsync):
    from mxtpu.streaming import StreamWriter
    w = StreamWriter(root, shard=0)
    t0 = time.perf_counter()
    for _ in range(n):
        w.append(payload, fsync=fsync)
    w.close()
    dt = time.perf_counter() - t0
    return {"records_s": round(n / dt, 1),
            "mb_s": round(n * len(payload) / dt / 1e6, 2)}


def bench_tail(root, n):
    from mxtpu.streaming import StreamReader
    from mxtpu.streaming.log import list_segments
    r = StreamReader(root, 0)
    t0 = time.perf_counter()
    got = 0
    for seq, _path, _sealed in list_segments(root, 0):
        records, _end, _ = r.read(seq)
        got += len(records)
    dt = time.perf_counter() - t0
    assert got == n, (got, n)
    return {"records_s": round(n / dt, 1)}


def bench_loop(root, n_records, batch):
    import mxtpu as mx
    from mxtpu.kvstore_async import ParameterServer
    from mxtpu.streaming import (ContinualTrainer, StreamingIter,
                                 StreamWriter, encode_record)

    w = StreamWriter(root, shard=0)
    for i in range(n_records):
        w.append(encode_record(
            "r%d" % i, (np.full((8,), i % 7, np.float32),),
            np.float32(i % 7)))
    w.close()

    srv = ParameterServer().start()
    os.environ["MXTPU_PS_ADDRS"] = srv.address
    os.environ["MXTPU_PROC_ID"] = "0"
    os.environ["MXTPU_NUM_PROCS"] = "1"
    kv = mx.kv.create("dist_async")
    try:
        it = StreamingIter(kv, root, group="bench", batch_size=batch,
                           idle_timeout=0.2, poll=0.005)

        def grad_fn(params, records):
            tot = np.zeros((8,), np.float32)
            for _rid, feats, _label in records:
                tot += feats[0]
            return {"acc": tot}

        tr = ContinualTrainer(kv, it,
                              {"acc": np.zeros((8,), np.float32)},
                              grad_fn)
        t0 = time.perf_counter()
        steps = tr.run()
        dt = time.perf_counter() - t0
        assert steps == (n_records + batch - 1) // batch, steps

        # replay-refusal rate: re-send one frame's worth of dups
        parts = [("acc", np.ones((8,), np.float32))]
        offs = kv.stream_offsets("bench")
        (shard, seg), (offset, _fin) = sorted(offs.items())[0]
        n_dup = max(50, n_records // 4)
        t0 = time.perf_counter()
        for _ in range(n_dup):
            kv.stream_push(parts, ("bench", shard, seg, offset, True))
        dup_dt = time.perf_counter() - t0
        assert srv._stream_dup >= n_dup
        return {"steps_s": round(steps / dt, 1),
                "records_s": round(n_records / dt, 1),
                "dup_refused_s": round(n_dup / dup_dt, 1)}
    finally:
        kv.close()
        srv.stop()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int,
                    default=500 if TINY else 20000)
    ap.add_argument("--payload-bytes", type=int, default=512)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--no-write", action="store_true")
    args = ap.parse_args()

    payload = os.urandom(args.payload_bytes)
    out = {"bench": "streaming_loopback", "tiny": TINY,
           "records": args.records,
           "payload_bytes": args.payload_bytes,
           "batch": args.batch}
    tmp = tempfile.mkdtemp(prefix="mxtpu_stream_bench_")
    try:
        adir = os.path.join(tmp, "append")
        out["append"] = bench_append(adir, args.records, payload,
                                     fsync=False)
        out["tail"] = bench_tail(adir, args.records)
        out["append_fsync"] = bench_append(
            os.path.join(tmp, "fsync"),
            max(50, args.records // 20), payload, fsync=True)
        out["loop"] = bench_loop(os.path.join(tmp, "loop"),
                                 args.records if TINY
                                 else min(args.records, 4000),
                                 args.batch)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    line = json.dumps(out, sort_keys=True)
    print(line)
    if not args.no_write:
        with open(os.path.join(ROOT, "docs",
                               "streaming_bench.json"), "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
